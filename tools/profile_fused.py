"""Subtractive profile of the fused learner step on the real chip.

Per-op device traces don't cross the tunneled-TPU boundary reliably, so the
breakdown is measured by *ablation*: build K-step scan variants of the fused
program with trailing stages deleted, time each honestly (host transfer
forces execution — bench.py methodology), and difference them:

    noop scan            -> scan + dispatch floor
    + sampler            -> two-level inverse-CDF cost
    + batch gather       -> HBM gather of 32 (obs, next_obs) rows
    + forward            -> online (2B) + target (B) forwards
    + backward           -> grad pass
    + optimizer          -> RMSProp traffic (the HBM suspect)
    + restamp            -> priority scatter
    == full fused step

Every variant's outputs are threaded into a scalar the host reads, so XLA
cannot dead-code-eliminate the stage under test.  Writes PROFILE.md.

Usage:  python tools/profile_fused.py [--steps-per-call 1024] [--capacity 100000]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure_roofline(repeats: int = 5, size_elems: int = 1 << 28,
                     scan_len: int = 8) -> dict:
    """Effective HBM bandwidth on THIS chip, slope-timed.

    Two kernels over a 1 GiB float32 array inside a ``lax.scan`` (so the
    compiler cannot batch or elide iterations — each consumes the last):

      * stream:  x = x * c       (reads + writes 4·N bytes per iteration)
      * reduce:  s += sum(x)·c   (reads 4·N bytes per iteration)

    GB/s = bytes/iteration · scan_len / slope-timed seconds-per-call —
    the number the fused step's per-step HBM-bytes floor must be divided
    by (replacing the datasheet figure the round-3 verdict flagged as
    asserted-not-measured).
    """
    import jax
    import jax.numpy as jnp

    from ape_x_dqn_tpu.utils.profiling import slope_timing

    n = size_elems
    gib = n * 4 / (1 << 30)

    @jax.jit
    def stream(x, s):
        def body(carry, _):
            x, s = carry
            x = x * jnp.float32(1.0000001)
            return (x, s + x[0]), None
        (x, s), _ = jax.lax.scan(body, (x, s), None, length=scan_len)
        return x, s

    @jax.jit
    def reduce(x, s):
        def body(s, _):
            # The reduction's OPERAND depends on the carry (a dynamic
            # slice offset computed from s), so loop-invariant code motion
            # cannot hoist the 1 GiB sum out of the scan — summing a
            # closed-over x (even scaled by the carry afterwards) would
            # let XLA compute it once and report scan_len x the real
            # bandwidth.
            off = jnp.abs(s.astype(jnp.int32)) & 7
            window = jax.lax.dynamic_slice(x, (off,), (n - 8,))
            return jnp.sum(window) * jnp.float32(1e-7) \
                + s * jnp.float32(1e-9), None
        s, _ = jax.lax.scan(body, s, None, length=scan_len)
        return x, s

    env = {"x": jnp.ones((n,), jnp.float32), "s": jnp.zeros(())}

    def run(prog):
        def fn():
            env["x"], env["s"] = prog(env["x"], env["s"])
        return fn

    def force():
        _ = float(np.asarray(env["s"]))

    secs = slope_timing(
        {"stream": run(stream), "reduce": run(reduce)},
        force, n_small=2, n_big=8, repeats=repeats,
    )
    out = {
        "array_gib": round(gib, 2),
        "scan_len": scan_len,
        # stream moves read+write = 2 passes; reduce reads 1 pass.
        "stream_gbps": round(2 * gib * scan_len / secs["stream"], 1),
        "reduce_gbps": round(gib * scan_len / secs["reduce"], 1),
        "seconds_per_call": {k: round(v, 4) for k, v in secs.items()},
    }
    del env["x"]
    return out


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--steps-per-call", type=int, default=1024)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--capacity", type=int, default=100_000)
    p.add_argument("--repeats", type=int, default=5)
    p.add_argument("--out", default="PROFILE.md")
    p.add_argument("--try-trace", action="store_true",
                   help="also attempt a jax.profiler trace into ./profiles/")
    p.add_argument("--skip-roofline", action="store_true",
                   help="skip the HBM bandwidth microbench (~30s)")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp

    from ape_x_dqn_tpu.learner.train_step import (
        build_train_step,
        init_train_state,
        make_optimizer,
    )
    from ape_x_dqn_tpu.models.dueling import build_network
    from ape_x_dqn_tpu.ops import losses
    from ape_x_dqn_tpu.replay.device import (
        device_replay_add,
        device_replay_sample,
        device_replay_update_priorities,
        init_device_replay,
    )
    from ape_x_dqn_tpu.utils.profiling import slope_timing, trace

    B, K, C = args.batch_size, args.steps_per_call, args.capacity
    obs_shape, A = (84, 84, 1), 4
    net = build_network("conv", A)
    opt = make_optimizer("rmsprop", max_grad_norm=None,
                         second_moment_dtype=jnp.bfloat16)
    step_fn = build_train_step(net, opt, sync_in_step=False, jit=False)

    rng = np.random.default_rng(0)
    replay = init_device_replay(C, obs_shape)
    add = jax.jit(device_replay_add, donate_argnums=(0,))
    from ape_x_dqn_tpu.types import NStepTransition

    M = 2048
    chunk = jax.device_put(NStepTransition(
        obs=jnp.asarray(rng.integers(0, 255, (M, *obs_shape), dtype=np.uint8)),
        action=jnp.asarray(rng.integers(0, A, (M,), dtype=np.int32)),
        reward=jnp.asarray(rng.normal(size=(M,)).astype(np.float32)),
        discount=jnp.full((M,), 0.97, jnp.float32),
        next_obs=jnp.asarray(rng.integers(0, 255, (M, *obs_shape), dtype=np.uint8)),
    ))
    for _ in range(C // M + 1):
        replay = add(replay, chunk, jnp.ones((M,), jnp.float32))
    state = init_train_state(
        net, opt, jax.random.PRNGKey(0),
        jnp.zeros((1, *obs_shape), jnp.uint8), target_dtype=jnp.bfloat16,
    )

    def loss_only(t_state, batch):
        t = batch.transition
        q_both = net.apply(
            t_state.params, jnp.concatenate([t.obs, t.next_obs], axis=0)
        )[2]
        q_values, q_next_online = q_both[:B], q_both[B:]
        q_next_target = net.apply(t_state.target_params, t.next_obs)[2]
        targets = losses.double_q_target(
            q_next_online, q_next_target, t.reward, t.discount
        )
        delta = losses.td_error(q_values, t.action, targets)
        return losses.td_loss(delta, batch.is_weights, kind="huber")

    # --- scan variants.  Each body returns a scalar metric that depends on
    # every stage it contains (anti-DCE), and each program has signature
    # (state, replay, rng) -> (state, replay, metric_sum).
    def make_scan(body):
        def prog(t_state, r_state, rng_key):
            def wrapped(carry, step_rng):
                t, r = carry
                t, r, m = body(t, r, step_rng)
                return (t, r), m
            rngs = jax.random.split(rng_key, K)
            (t_state, r_state), ms = jax.lax.scan(
                wrapped, (t_state, r_state), rngs
            )
            return t_state, r_state, jnp.sum(ms)
        return jax.jit(prog, donate_argnums=(0, 1))

    def b_noop(t, r, k):
        return t, r, jax.random.uniform(k, ())

    def b_sampler(t, r, k):
        # Sampler indices + IS weights, but no row gather of frames.
        from ape_x_dqn_tpu.ops.pallas.sampling import sample_indices
        total = jnp.sum(r.mass)
        u = jax.random.uniform(k, (B,))
        targets = (jnp.arange(B, dtype=jnp.float32) + u) * (total / B)
        idx = sample_indices(r.mass, jnp.minimum(targets, total * (1 - 1e-7)))
        return t, r, jnp.sum(idx) + jnp.sum(r.mass[idx])

    def b_gather(t, r, k):
        batch = device_replay_sample(r, k, B, 0.4)
        m = (jnp.sum(batch.transition.obs.astype(jnp.float32))
             + jnp.sum(batch.transition.next_obs.astype(jnp.float32))
             + jnp.sum(batch.is_weights))
        return t, r, m

    def b_forward(t, r, k):
        batch = device_replay_sample(r, k, B, 0.4)
        return t, r, loss_only(t, batch)

    def b_backward(t, r, k):
        batch = device_replay_sample(r, k, B, 0.4)
        loss, grads = jax.value_and_grad(
            lambda p: loss_only(t.replace(params=p), batch)
        )(t.params)
        # One reduction pass keeps all grads alive (adds ~one grad read).
        gsum = sum(jnp.sum(g) for g in jax.tree_util.tree_leaves(grads))
        return t, r, loss + gsum * 1e-12

    def b_train(t, r, k):
        batch = device_replay_sample(r, k, B, 0.4)
        t, metrics = step_fn(t, batch)
        return t, r, metrics.loss

    def b_full(t, r, k):
        batch = device_replay_sample(r, k, B, 0.4)
        t, metrics = step_fn(t, batch)
        r = device_replay_update_priorities(r, batch.indices, metrics.priorities)
        return t, r, metrics.loss

    stages = [
        ("noop", b_noop), ("sampler", b_sampler), ("gather", b_gather),
        ("forward", b_forward), ("backward", b_backward),
        ("train", b_train), ("full", b_full),
    ]
    progs = {name: make_scan(body) for name, body in stages}

    env = {"state": state, "replay": replay, "key": jax.random.PRNGKey(1)}

    def run_variant(name):
        def fn():
            env["key"], sub = jax.random.split(env["key"])
            env["state"], env["replay"], env["m"] = progs[name](
                env["state"], env["replay"], sub
            )
        return fn

    def force():
        _ = float(np.asarray(env["m"]))

    t0 = time.perf_counter()
    seconds = slope_timing(
        {name: run_variant(name) for name, _ in stages},
        force, n_small=2, n_big=8, repeats=args.repeats,
    )
    wall = time.perf_counter() - t0

    us = {name: s / K * 1e6 for name, s in seconds.items()}
    deltas = {
        "scan+dispatch floor": us["noop"],
        "sampler (two-level CDF)": us["sampler"] - us["noop"],
        "batch gather (rows from ring)": us["gather"] - us["sampler"],
        "forward (online 2B + target B)": us["forward"] - us["gather"],
        "backward (+1 grad-read pass)": us["backward"] - us["forward"],
        "optimizer (RMSProp update)": us["train"] - us["backward"],
        "priority restamp (scatter)": us["full"] - us["train"],
    }

    roofline = None
    if not args.skip_roofline:
        roofline = measure_roofline(repeats=args.repeats)

    trace_note = "not attempted"
    if args.try_trace:
        os.makedirs("profiles", exist_ok=True)
        with trace("profiles") as started:
            if started:
                run_variant("full")()
                force()
        trace_note = (
            "written to profiles/ (TensorBoard)" if started
            else "unavailable on this platform (plugin cannot trace the tunnel)"
        )

    dev = jax.devices()[0].device_kind
    lines = [
        "# PROFILE — fused learner step breakdown (subtractive ablation)",
        "",
        f"Chip: **{dev}** · batch {B} · K={K} steps/dispatch · replay C={C:,}",
        f"· repeats={args.repeats} (min) · measured {time.strftime('%Y-%m-%d')}"
        f" · total wall {wall:.0f}s",
        "",
        "Method: K-step `lax.scan` variants with trailing stages deleted,",
        "each output data-threaded to a host-read scalar (anti-DCE); honest",
        "forcing via host transfer (`block_until_ready` is a no-op through",
        "the tunnel — see bench.py), and **slope timing**: the tunnel charges",
        "a fixed ~140 ms to the first dispatch after any host sync, so each",
        "variant is timed as the marginal cost of chained calls",
        "(T(8 calls) − T(2 calls)) / 6, which cancels the fixed term.",
        "Stage cost = difference of adjacent variants.",
        "`tools/profile_fused.py` regenerates this file.",
        "",
        "| cumulative variant | µs/step |",
        "|---|---|",
    ]
    for name, _ in stages:
        lines.append(f"| {name} | {us[name]:.1f} |")
    lines += ["", "| stage (delta) | µs/step |", "|---|---|"]
    for k, v in deltas.items():
        lines.append(f"| {k} | {v:.1f} |")
    if roofline is not None:
        # Bold, NOT a markdown heading: regeneration preserves everything
        # from the first heading (hand-written appendices) — a generated
        # heading here would get double-preserved on the next run.
        lines += [
            "",
            "**Measured HBM roofline (this chip, slope-timed):**",
            "",
            f"| kernel ({roofline['array_gib']} GiB f32, scan×"
            f"{roofline['scan_len']}) | effective GB/s |",
            "|---|---|",
            f"| stream (read+write) | {roofline['stream_gbps']} |",
            f"| reduce (read-only) | {roofline['reduce_gbps']} |",
            "",
            "The per-step byte floor below divides by THESE numbers, not "
            "the datasheet figure.",
        ]
    lines += [
        "",
        f"jax.profiler trace: {trace_note}",
        "",
        "Raw seconds-per-variant: `" + json.dumps(
            {k: round(v, 4) for k, v in seconds.items()}) + "`",
        "",
    ]
    # Preserve hand-written analysis sections (everything from the first
    # "## " heading on): this tool owns only the generated ablation block
    # above them — a rerun must not wipe the round-notes appendices.
    preserved = ""
    if os.path.exists(args.out):
        import re

        old = open(args.out).read()
        # Any heading level counts as "hand-written starts here" — the
        # generated block's own "# PROFILE" title is line 1, so skip it.
        m = re.search(r"\n#{1,6} ", old)
        if m:
            preserved = old[m.start():]
    with open(args.out, "w") as f:
        f.write("\n".join(lines) + preserved)
    print(json.dumps({"us_per_step": {k: round(v, 1) for k, v in us.items()},
                      "deltas": {k: round(v, 1) for k, v in deltas.items()}}))


if __name__ == "__main__":
    main()
