#!/usr/bin/env bash
# Tier-1 verify — the ONE blessed entry point for builders and CI.
# This is the ROADMAP.md "Tier-1 verify" command verbatim; if the ROADMAP
# command changes, change it HERE too (they must stay character-identical
# modulo this wrapper's cd).
cd "$(dirname "$0")/.." || exit 1
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
