#!/usr/bin/env bash
# Tier-1 verify — the ONE blessed entry point for builders and CI.
# Gate 1: compileall — an import-time syntax regression anywhere in the
#         package or tools fails in seconds, not after an 870 s pytest run.
# Gate 2: xp_transport smoke — bench.py's CI-sized transport point +
#         SIGKILL barrage (host-only, no backend probe), so a regression
#         in the experience transport or bench wiring can't reach the
#         driver unseen.
# Gate 3: checkpoint round-trip smoke — train on the tiny config with
#         incremental checkpointing, SIGKILL mid-run, resume from the
#         committed manifest and train past it (tools/ckpt_smoke.py).
# Gate 4: observability smoke — the process-actor pipeline with the
#         exporter on an ephemeral port: scrape /metrics + /varz +
#         /healthz, SIGKILL a worker, assert the salvaged shm stats
#         block lands as a post-mortem file and lineage spans complete
#         (tools/obs_smoke.py).
# Gate 5: pipeline-overlap smoke — a short OVERLAPPED fused run on CPU
#         (learner.pipeline_depth=4 + sync_every): asserts host_syncs <=
#         steps/sync_every + slack and a clean flush-at-exit (zero calls
#         left in flight, finite loss) — tools/pipeline_smoke.py.
# Gate 6: chaos smoke — the fault-tolerance contract, CI-sized: a
#         2-worker supervised run takes one SIGKILL (supervised respawn),
#         one SIGKILL + injected torn ring record (salvage counts it,
#         never ingests it), then a committed APXC chunk is bit-flipped
#         and the resume must walk the chain back (fallback restore) and
#         train past the restored step — tools/chaos_smoke.py.
# Gate 7: tiered-replay spill smoke — a hot-budgeted replay (most spans
#         cold on disk) must sample bit-exactly against its dense twin
#         with evictions forced between every op, then survive a SIGKILL
#         mid-spill: the committed chain restores bit-exactly (cold
#         spans adopted in place, CRC-verified) and trains past the
#         restored step — tools/spill_smoke.py.
# Gate 8: network-transport smoke — the process-actor pipeline on the
#         TCP experience backend (actor.transport=tcp, loopback): every
#         non-shm worker contributes verified non-torn chunks to real
#         training steps, an injected partial frame is detected as torn
#         and never ingested, the displaced worker reconnects and
#         resumes, a SIGKILLed worker respawns onto a fresh connection,
#         and param fan-out cost is recorded per push; then the
#         wire-efficiency leg — net_codec=zlib + coalescing + frame
#         dedup through a hello-negotiated connection into pool.poll,
#         asserting BIT-EXACT ingest and wire/logical < 1.0 with zero
#         torn frames (tools/net_smoke.py).
# Gate 9: serving-net smoke — the network serving tier end to end: a
#         2-replica fleet on ephemeral ports (router + delta param hub),
#         a closed-loop client burst over real sockets, a hot param
#         reload fanned out as page-deltas MID-BURST, one replica
#         SIGKILLed mid-burst (drained, respawned, full-synced), zero
#         dropped requests and fresh param_version on both replicas
#         (tools/serving_net_smoke.py).
# Gate 10: replay-service smoke — replay as a service end to end: a
#         2-shard replay fleet (own processes, own checkpoint chains),
#         TWO CLI learners attached over framed RPC, a remote worker
#         host joined via tools/host_join.py, one shard SIGKILLed
#         mid-run by the seeded kill-shard-at-step drill; both learners
#         must keep training through the outage (typed degradation,
#         buffered priority write-backs), the respawned shard must
#         recover bit-exact-or-typed from its chain (digest-verified
#         against the frozen chain), write-backs must flush, and no
#         torn frame may appear on either side
#         (tools/replay_svc_smoke.py).
# Gate 11: central-inference smoke — the SEED-style production story end
#         to end: a 2-replica routed serving fleet (serve.py children
#         with the trainer's --run-token), a process-actor trainer whose
#         workers are PARAMLESS (actor.inference=central, every action
#         selected through the router into a replica's micro-batcher,
#         ε worker-side on the global ladder slice), trainer publishes
#         fanned to the fleet as page-deltas, one replica SIGKILLed
#         mid-run; training must reach its step target with zero torn
#         frames on either side, zero worker deaths, fresh
#         param_version in replies, and the replica respawned
#         (tools/central_inference_smoke.py).
# Gate 12: fleet-observability smoke — the rollup plane end to end: a
#         trainer attached to a 2-shard replay fleet (full tracing) +
#         a 2-replica routed serving fleet, a FleetAggregator scraping
#         all five endpoints into one rollup (histograms merged across
#         shards AND replicas, a >=3-pid cross-tier trace timeline),
#         one shard SIGKILLed mid-run: the endpoint-liveness SLO must
#         fire a damped slo_breach, the shard must respawn, and
#         slo_clear must follow — with the rollup serving throughout
#         (tools/fleet_obs_smoke.py).
# Gate 13: elastic-autopilot smoke — ROADMAP item 3's done-condition,
#         CI-sized: an in-process trainer (process actors under slow-env
#         chaos, autopilot enabled) next to a 1-replica serving fleet
#         with sleep-bound service time, driven by a loadgen QPS step
#         schedule.  The controller must decide NOTHING while every SLO
#         is green; under the surge it must spawn replica 2 (one step,
#         busy-held) and the windowed serving p99 must re-hold; in the
#         idle phase it must retire the replica on the zero-drop drain
#         path (zero loadgen timeouts/errors across the run); and after
#         kill-half-the-workers quarantines a wid, it must grow the
#         reserved wid on the same ε-ladder partition until the windowed
#         age-of-experience p95 re-holds (tools/autopilot_smoke.py).
# Gate 14: elastic-replay smoke — the replay service as the third
#         autopilot-governed fleet, on the fleet discovery plane: a
#         standalone membership registry, a 2-shard replay fleet that
#         ANNOUNCES every shard, a from_registry client and a
#         bind_registry aggregator (no endpoints file in the driver
#         anywhere).  At the 2-shard floor the idle impulse must be
#         suppressed at_min with zero decisions; under ingest pressure
#         the per-shard add-QPS SLO must breach and the autopilot must
#         grow 2->3 (membership propagating the new shard to client and
#         sensor); when ingest stops, the idle burn window must retire
#         the shard through the digest-proven drain -> fingerprint ->
#         restore -> prove -> re-add handoff with ZERO lost transitions,
#         the client sampling throughout (tools/elastic_replay_smoke.py).
# Gate 15: apexlint — the repo's static invariant checkers
#         (ape_x_dqn_tpu/analysis/ + tools/lint.py; docs/INVARIANTS.md):
#         import-lightness of the no-jax child modules, the wire
#         kind/magic registry, config coverage, metrics-doc coverage,
#         shm discipline, typed-error discipline.  Purely static (~2 s;
#         hard budget 20 s), fails on any finding NEW relative to the
#         committed baseline.
# Gate 16: the ROADMAP.md "Tier-1 verify" command verbatim; if the ROADMAP
#         command changes, change it HERE too (they must stay
#         character-identical modulo this wrapper's cd).
cd "$(dirname "$0")/.." || exit 1
timeout -k 10 120 python -m compileall -q ape_x_dqn_tpu tools || exit 1
timeout -k 10 180 env JAX_PLATFORMS=cpu python bench.py --xp-transport-smoke > /tmp/_t1_xp.log 2>&1 || { echo "xp_transport smoke FAILED:"; cat /tmp/_t1_xp.log; exit 1; }
timeout -k 10 300 env JAX_PLATFORMS=cpu python tools/ckpt_smoke.py > /tmp/_t1_ckpt.log 2>&1 || { echo "checkpoint smoke FAILED:"; cat /tmp/_t1_ckpt.log; exit 1; }
timeout -k 10 480 env JAX_PLATFORMS=cpu python tools/obs_smoke.py > /tmp/_t1_obs.log 2>&1 || { echo "obs smoke FAILED:"; cat /tmp/_t1_obs.log; exit 1; }
timeout -k 10 300 env JAX_PLATFORMS=cpu python tools/pipeline_smoke.py --steps 2048 > /tmp/_t1_pipe.log 2>&1 || { echo "pipeline smoke FAILED:"; cat /tmp/_t1_pipe.log; exit 1; }
timeout -k 10 480 env JAX_PLATFORMS=cpu python tools/chaos_smoke.py > /tmp/_t1_chaos.log 2>&1 || { echo "chaos smoke FAILED:"; cat /tmp/_t1_chaos.log; exit 1; }
timeout -k 10 180 env JAX_PLATFORMS=cpu python tools/spill_smoke.py > /tmp/_t1_spill.log 2>&1 || { echo "spill smoke FAILED:"; cat /tmp/_t1_spill.log; exit 1; }
timeout -k 10 480 env JAX_PLATFORMS=cpu python tools/net_smoke.py > /tmp/_t1_net.log 2>&1 || { echo "net smoke FAILED:"; cat /tmp/_t1_net.log; exit 1; }
timeout -k 10 480 env JAX_PLATFORMS=cpu python tools/serving_net_smoke.py > /tmp/_t1_snet.log 2>&1 || { echo "serving-net smoke FAILED:"; cat /tmp/_t1_snet.log; exit 1; }
timeout -k 10 600 env JAX_PLATFORMS=cpu python tools/replay_svc_smoke.py > /tmp/_t1_rsvc.log 2>&1 || { echo "replay-svc smoke FAILED:"; cat /tmp/_t1_rsvc.log; exit 1; }
timeout -k 10 480 env JAX_PLATFORMS=cpu python tools/central_inference_smoke.py > /tmp/_t1_central.log 2>&1 || { echo "central-inference smoke FAILED:"; cat /tmp/_t1_central.log; exit 1; }
timeout -k 10 480 env JAX_PLATFORMS=cpu python tools/fleet_obs_smoke.py > /tmp/_t1_fleet.log 2>&1 || { echo "fleet-obs smoke FAILED:"; cat /tmp/_t1_fleet.log; exit 1; }
timeout -k 10 500 env JAX_PLATFORMS=cpu python tools/autopilot_smoke.py > /tmp/_t1_autopilot.log 2>&1 || { echo "autopilot smoke FAILED:"; cat /tmp/_t1_autopilot.log; exit 1; }
timeout -k 10 320 python tools/elastic_replay_smoke.py > /tmp/_t1_ereplay.log 2>&1 || { echo "elastic-replay smoke FAILED:"; cat /tmp/_t1_ereplay.log; exit 1; }
timeout -k 5 20 python -m tools.lint --fail-on-new > /tmp/_t1_lint.log 2>&1 || { echo "apexlint gate FAILED:"; cat /tmp/_t1_lint.log; exit 1; }
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c); exit $rc
