"""Hour-scale elasticity soak — process-mode training under random SIGKILLs.

Round-3 verdict item 8 / SURVEY §5 failure detection (the reference's story
is "actor crash = silent loss of that actor"): run the async fused pipeline
with process-mode actors for ``--minutes``, SIGKILL a random worker every
``--kill-every`` seconds, and assert at the end that

  * the learner's step counter advanced monotonically the whole time,
  * every kill was followed by a supervisor respawn (restarts ≥ kills,
    within the configured budget),
  * a final resume-from-checkpoint continues from the saved step with the
    replay intact.

Writes a JSONL heartbeat stream (one record every ``--sample-every``
seconds: learner step, actor steps, restarts, replay size) plus a final
summary record — the committed soak artifact.

    python tools/soak.py --minutes 35 --kill-every 150 \
        --out demos/soak_metrics.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_cfg(ckpt_dir: str, resume: bool = False):
    from ape_x_dqn_tpu.config import ApexConfig

    cfg = ApexConfig()
    cfg.env.name = "fake-atari"          # real 84×84 conv frames, no ALE
    cfg.network = "conv"
    cfg.actor.num_actors = 32
    cfg.actor.T = 1_000_000_000
    cfg.actor.flush_every = 16
    cfg.actor.sync_every = 200
    cfg.actor.mode = "process"
    cfg.actor.num_workers = 2
    cfg.actor.worker_nice = 5
    cfg.learner.device_replay = True
    cfg.learner.sample_ahead = True
    cfg.learner.steps_per_call = 512
    cfg.learner.publish_every = 2048
    cfg.learner.min_replay_mem_size = 2_000
    cfg.learner.optimizer = "rmsprop"
    cfg.learner.max_grad_norm = None
    cfg.learner.second_moment_dtype = "bfloat16"
    cfg.learner.target_dtype = "bfloat16"
    cfg.learner.total_steps = 1_000_000_000
    cfg.learner.checkpoint_every = 8192
    cfg.learner.checkpoint_dir = ckpt_dir
    cfg.learner.restore_from = ckpt_dir if resume else False
    cfg.replay.capacity = 50_000
    return cfg.validate()


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--minutes", type=float, default=35.0)
    p.add_argument("--kill-every", type=float, default=150.0,
                   help="seconds between randomized worker SIGKILLs")
    p.add_argument("--sample-every", type=float, default=15.0)
    p.add_argument("--out", default="demos/soak_metrics.jsonl")
    p.add_argument("--ckpt-dir", default="/tmp/soak_ckpt")
    p.add_argument("--max-restarts", type=int, default=1000)
    args = p.parse_args()

    from ape_x_dqn_tpu.runtime.async_pipeline import AsyncPipeline
    from ape_x_dqn_tpu.utils.metrics import MetricLogger

    import shutil

    shutil.rmtree(args.ckpt_dir, ignore_errors=True)
    cfg = build_cfg(args.ckpt_dir)
    devnull = open(os.devnull, "w")
    pipe = AsyncPipeline(cfg, logger=MetricLogger(stream=devnull),
                         log_every=10**9)
    pipe.worker.pool.max_restarts = args.max_restarts

    run_err = []

    def run():
        try:
            pipe.run(learner_steps=10**12, warmup_timeout=600.0)
        except Exception as e:  # noqa: BLE001 — surfaced in the summary
            run_err.append(f"{type(e).__name__}: {e}")

    t = threading.Thread(target=run, daemon=True)
    t.start()

    out = open(args.out, "w")
    t0 = time.time()
    deadline = t0 + args.minutes * 60.0
    next_kill = t0 + args.kill_every
    next_sample = t0
    kills = 0
    steps_seen = []
    rng = random.Random(0)
    ok_monotone = True
    while time.time() < deadline and t.is_alive():
        now = time.time()
        if now >= next_sample:
            next_sample = now + args.sample_every
            rec = {
                "t": round(now - t0, 1),
                "learner_step": pipe.learner_step,
                "actor_steps": pipe.worker.actor_steps,
                "restarts": pipe.worker.restarts,
                "replay_size": pipe.fused.size if pipe.fused else None,
                "kills": kills,
            }
            if steps_seen and rec["learner_step"] < steps_seen[-1]:
                ok_monotone = False
            steps_seen.append(rec["learner_step"])
            out.write(json.dumps(rec) + "\n")
            out.flush()
        if now >= next_kill:
            next_kill = now + args.kill_every
            procs = [q for q in pipe.worker.pool._procs if q.is_alive()]
            if procs:
                victim = rng.choice(procs)
                try:
                    # Races the supervisor's respawn/exit by design — a
                    # victim that died between the snapshot and the kill
                    # just skips this round.
                    os.kill(victim.pid, signal.SIGKILL)
                except ProcessLookupError:
                    continue
                kills += 1
                out.write(json.dumps(
                    {"t": round(now - t0, 1), "event": "SIGKILL",
                     "pid": victim.pid}) + "\n")
                out.flush()
        time.sleep(1.0)

    final_step = pipe.learner_step
    pipe.stop_event.set()
    t.join(timeout=120.0)
    devnull.close()

    # Resume leg: a fresh pipeline restores the newest checkpoint and
    # trains a short continuation.
    from ape_x_dqn_tpu.utils.checkpoint import latest_step

    ckpt_step = latest_step(args.ckpt_dir)
    resume_ok, resume_from, resume_to = False, None, None
    if ckpt_step:
        cfg2 = build_cfg(args.ckpt_dir, resume=True)
        devnull = open(os.devnull, "w")
        pipe2 = AsyncPipeline(cfg2, logger=MetricLogger(stream=devnull),
                              log_every=10**9)
        resume_from = pipe2.learner_step
        result = pipe2.run(
            learner_steps=resume_from + 4 * cfg2.learner.steps_per_call,
            warmup_timeout=600.0,
        )
        resume_to = result["step"]
        resume_ok = resume_from >= ckpt_step and resume_to > resume_from
        devnull.close()

    grew = steps_seen and steps_seen[-1] > (steps_seen[0] if steps_seen else 0)
    summary = {
        "summary": True,
        "wall_minutes": round((time.time() - t0) / 60.0, 1),
        "final_learner_step": final_step,
        "kills": kills,
        "restarts": pipe.worker.restarts,
        "monotone_progress": ok_monotone,
        "progress_grew": bool(grew),
        "run_error": run_err[0] if run_err else None,
        "checkpoint_step": ckpt_step,
        "resume_from": resume_from,
        "resume_to": resume_to,
        "resume_ok": resume_ok,
        "passed": (
            ok_monotone and bool(grew) and kills > 0
            and pipe.worker.restarts >= kills - 1 and not run_err
            and resume_ok
        ),
    }
    out.write(json.dumps(summary) + "\n")
    out.close()
    print(json.dumps(summary))
    return 0 if summary["passed"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
