"""Price the replay-service RPC plane against in-process replay — the
number ROADMAP item 1 asked for: what does moving the replay out of the
learner's address space cost per sampled batch?

Three legs, same workload (Atari-shaped 84x84x1 uint8 frames, batch-32
sample + priority write-back per iteration, warm buffer):

  * ``in_process`` — PrioritizedReplay in this process (the baseline
    every learner ran before replay-as-a-service);
  * ``rpc_1shard`` — the same replay behind one ReplayShardServer
    SUBPROCESS on loopback (framed RPC, dedup+zlib bodies): the full
    serialization + syscall + scheduling cost of the service;
  * ``rpc_2shard`` — two shards (the fleet shape), mass-weighted shard
    choice per sample.

On a 1-core host the RPC legs price CPU (serialize/deflate/copy), not
network — the same caveat the xp_net bench carries.  Output: one JSON
line (bench.py `replay_svc` section parses it; committed as
demos/replay_svc.json).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

import numpy as np  # noqa: E402


def _fill(target, rng, rows, obs_shape, chunk=256):
    class B:
        pass

    added = 0
    while added < rows:
        n = min(chunk, rows - added)
        b = B()
        obs = rng.integers(0, 255, (n, *obs_shape), dtype=np.uint8)
        b.obs = obs
        # n-step-overlap shape so the dedup layer sees production
        # redundancy on the add path.
        b.next_obs = np.roll(obs, -1, axis=0)
        b.action = rng.integers(0, 4, n).astype(np.int32)
        b.reward = rng.normal(size=n).astype(np.float32)
        b.discount = np.full(n, 0.99, np.float32)
        target.add((np.abs(rng.normal(size=n)) + 0.1).astype(np.float64), b)
        added += n


def _measure(target, rng, iters, batch):
    lat = []
    t0 = time.perf_counter()
    for _ in range(iters):
        t1 = time.perf_counter()
        b = target.sample(batch, beta=0.4, rng=rng)
        target.update_priorities(
            b.indices, np.abs(rng.normal(size=batch)) + 0.1
        )
        lat.append(time.perf_counter() - t1)
    wall = time.perf_counter() - t0
    lat_ms = np.asarray(sorted(lat)) * 1e3
    return {
        "iters": iters,
        "batch": batch,
        "samples_per_s": round(iters * batch / wall, 1),
        "ms_per_iter_p50": round(float(lat_ms[len(lat_ms) // 2]), 3),
        "ms_per_iter_p95": round(float(lat_ms[int(0.95 * len(lat_ms))]), 3),
        "wall_s": round(wall, 2),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="replay_svc_bench")
    ap.add_argument("--capacity", type=int, default=16_384)
    ap.add_argument("--rows", type=int, default=8_192)
    ap.add_argument("--iters", type=int, default=300)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--obs-shape", default="84,84,1")
    ap.add_argument("--out", default="-")
    args = ap.parse_args(argv)

    from ape_x_dqn_tpu.replay.buffer import PrioritizedReplay
    from ape_x_dqn_tpu.replay.service import (
        ReplayServiceFleet,
        ShardClient,
        ShardedReplayClient,
    )

    obs_shape = tuple(int(d) for d in args.obs_shape.split(","))
    report = {
        "config": {"capacity": args.capacity, "rows": args.rows,
                   "iters": args.iters, "batch": args.batch,
                   "obs_shape": list(obs_shape)},
    }

    # Leg 1: in-process baseline.
    rep = PrioritizedReplay(args.capacity, obs_shape)
    rng = np.random.default_rng(0)
    _fill(rep, rng, args.rows, obs_shape)
    report["in_process"] = _measure(rep, rng, args.iters, args.batch)
    del rep

    # RPC legs: the service, shards as real subprocesses on loopback.
    # codec=off and codec=zlib are separate legs on purpose: these
    # RANDOM frames are incompressible, so the zlib leg prices the
    # worst-case codec CPU (deflate tried, discarded as not-smaller on
    # replies; the dedup layer still wins on the overlapping add path)
    # while the off leg prices pure framing+copy+syscall.
    # codec=auto is the PR-12 gate: the hello still negotiates the zlib
    # CAPABILITY, but the shard compresses sample replies only while its
    # reply sends observe kernel-buffer backpressure — on an unloaded
    # loopback it should price like the off leg, not the zlib one.
    for shards, codec in ((1, "off"), (1, "zlib"), (1, "auto"), (2, "off")):
        leg_name = f"rpc_{shards}shard" + (
            f"_{codec}" if codec != "off" else ""
        )
        root = tempfile.mkdtemp(prefix=f"rsvc-bench-{shards}{codec}-")
        fleet = ReplayServiceFleet(
            shards, args.capacity, obs_shape, root_dir=root, codec=codec,
            save_every_s=0.0,      # pure serving cost: no ckpt traffic
        )
        fleet.start(timeout=60.0)
        cl = ShardedReplayClient.from_endpoints_file(
            fleet.endpoints_path, request_timeout_s=30.0,
        )
        try:
            rng = np.random.default_rng(0)
            _fill(cl, rng, args.rows, obs_shape)
            leg = _measure(cl, rng, args.iters, args.batch)
            # Wire economy on the RPC plane (shard-side accounting).
            wire = logical = zlib_n = raw_n = fw = 0
            for s in fleet.shards:
                sc = ShardClient(s.shard_id, "127.0.0.1", s.port,
                                 token=fleet.token, client_id=77,
                                 incarnation=s.incarnation, codec=codec)
                st = sc.shard_stats(timeout=10.0)
                wire += st["bytes_in"]
                logical += st["logical_bytes_in"]
                zlib_n += st.get("reply_zlib", 0)
                raw_n += st.get("reply_raw", 0)
                fw += st.get("reply_full_waits", 0)
                sc.close()
            leg["add_wire_over_logical"] = (
                round(wire / logical, 4) if logical else None
            )
            leg["codec"] = codec
            leg["reply_zlib"] = zlib_n
            leg["reply_raw"] = raw_n
            leg["reply_full_waits"] = fw
            report[leg_name] = leg
        finally:
            cl.close()
            fleet.stop()

    base = report["in_process"]["samples_per_s"]
    for k in ("rpc_1shard", "rpc_1shard_zlib", "rpc_1shard_auto",
              "rpc_2shard"):
        if k in report and base:
            report[k]["vs_in_process"] = round(
                report[k]["samples_per_s"] / base, 3
            )
    report["note"] = (
        "loopback subprocess shards on a shared host: the RPC legs price "
        "serialize/deflate/syscall CPU, not network bytes; "
        "add_wire_over_logical shows the dedup+zlib body economy"
    )
    line = json.dumps(report)
    if args.out == "-":
        print(line)
    else:
        with open(args.out, "w") as f:
            f.write(line + "\n")
        print(line)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
