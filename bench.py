"""Benchmark: fused learner step throughput on the real chip.

Prints ONE JSON line:
    {"metric": "learner_steps_per_sec", "value": N, "unit": "steps/s",
     "vs_baseline": R}

The metric is gradient steps/sec of the fully-fused train step (double-Q
target, loss, grads, RMSProp, target-sync, per-transition priorities in one
XLA program) on the flagship dueling conv net at the reference workload
scale (batch 32, 84x84x1 uint8 frames — reference parameters.json:3,23).

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is the
fraction of the north-star target rate prorated to this chip count:
50_000 steps/s on a v4-8 (4 chips) → 12_500 steps/s per chip.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

NORTH_STAR_PER_CHIP = 50_000 / 4.0


def main() -> None:
    from ape_x_dqn_tpu.learner.train_step import (
        build_train_step,
        init_train_state,
        make_optimizer,
    )
    from ape_x_dqn_tpu.models.dueling import build_network
    from ape_x_dqn_tpu.types import NStepTransition, PrioritizedBatch

    B, obs_shape, A = 32, (84, 84, 1), 4
    net = build_network("conv", A)
    opt = make_optimizer("rmsprop")
    state = init_train_state(
        net, opt, jax.random.PRNGKey(0), jnp.zeros((1, *obs_shape), jnp.uint8)
    )
    step = build_train_step(net, opt)

    rng = np.random.default_rng(0)
    n_batches = 8
    batches = [
        jax.device_put(
            PrioritizedBatch(
                transition=NStepTransition(
                    obs=rng.integers(0, 255, (B, *obs_shape), dtype=np.uint8),
                    action=rng.integers(0, A, (B,), dtype=np.int32),
                    reward=rng.normal(size=(B,)).astype(np.float32),
                    discount=np.full((B,), 0.97, np.float32),
                    next_obs=rng.integers(0, 255, (B, *obs_shape), dtype=np.uint8),
                ),
                indices=np.arange(B, dtype=np.int32),
                is_weights=np.ones((B,), np.float32),
            )
        )
        for _ in range(n_batches)
    ]

    # Warmup: compile + a few steps.
    for i in range(3):
        state, metrics = step(state, batches[i % n_batches])
    jax.block_until_ready(metrics.loss)

    steps = 600
    t0 = time.perf_counter()
    for i in range(steps):
        state, metrics = step(state, batches[i % n_batches])
    jax.block_until_ready(metrics.loss)
    dt = time.perf_counter() - t0

    rate = steps / dt
    print(
        json.dumps(
            {
                "metric": "learner_steps_per_sec",
                "value": round(rate, 1),
                "unit": "steps/s",
                "vs_baseline": round(rate / NORTH_STAR_PER_CHIP, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
