"""Benchmark: fused learner throughput on the real chip.

Prints ONE JSON line:
    {"metric": "learner_steps_per_sec", "value": N, "unit": "steps/s",
     "vs_baseline": R, ...extra fields...}

The metric is gradient steps/sec of the device-resident fused pipeline —
ingest → scan_K [prioritized sample → double-Q train step → priority
restamp] in ONE XLA dispatch (replay/device.py:build_fused_learn_step) —
on the flagship dueling conv net at the reference workload scale (batch 32,
84x84x1 uint8 frames, 100k-slot replay: reference parameters.json:3,23,28).

Methodology notes (both verified on hardware this round):
  * ``jax.block_until_ready`` does NOT actually block on this tunneled
    TPU platform — only a host transfer forces execution.  Round 1's
    BENCH_r01.json (7,337.8 steps/s) timed dispatch, not compute; the same
    workload measured honestly (``np.asarray`` on a value data-dependent on
    every step) sustains ~3.7k steps/s.  This bench forces every timed call
    through the serial train-state chain and pulls the final loss to host.
  * Per-dispatch overhead through the tunnel is ~2-22 ms, so K steps are
    fused per dispatch (lax.scan) and chunks are pre-staged on device —
    overlapping host transfers with device compute is the infeed queue's
    job (runtime/infeed.py), not the learner's.

``vs_baseline`` is the fraction of the north-star rate prorated per chip:
50_000 steps/s on a v4-8 (4 chips) → 12_500/chip (BASELINE.md).  The chip
here is a v5e (819 GB/s HBM vs v4's 1,228 GB/s); the fused step is HBM-bound
(RMSProp + params traffic), so the proration is conservative by ~1.5x.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

import numpy as np

NORTH_STAR_PER_CHIP = 50_000 / 4.0


def _probe_backend(timeout_s: float) -> dict:
    """Probe the real jax backend in a SUBPROCESS with a hard timeout.

    The tunneled TPU plugin HANGS (not errors) during an outage — observed
    multi-hour during round 5 (PROFILE.md) — so the probe must be a child
    process the parent can abandon, never an in-process ``jax.devices()``
    (the __graft_entry__.dryrun_multichip discipline).  A dead probe means
    the one-line JSON still ships with the host-only sections.
    """
    code = "import jax; d = jax.devices(); print('KIND=' + d[0].device_kind)"
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return {
            "ok": False,
            "elapsed_s": round(time.perf_counter() - t0, 1),
            "error": f"backend init exceeded {timeout_s}s "
            "(tunnel-outage signature: hang, not error)",
        }
    elapsed = round(time.perf_counter() - t0, 1)
    if proc.returncode != 0:
        lines = (proc.stderr or "").strip().splitlines()
        tail = lines[-1][:300] if lines else ""
        return {"ok": False, "elapsed_s": elapsed,
                "error": f"probe rc={proc.returncode}: {tail}"}
    kind = next(
        (l[5:] for l in proc.stdout.splitlines() if l.startswith("KIND=")),
        "unknown",
    )
    return {"ok": True, "elapsed_s": elapsed, "device_kind": kind}


def _serving_bench(clients: int = 32, duration: float = 6.0,
                   network: str = "conv", max_batch: int = 32,
                   timeout_s: float = 420.0) -> dict:
    """``serving_qps``: tools/loadgen.py in a CPU-pinned subprocess.

    Host-only by construction (the child forces ``jax_platforms=cpu``
    before its backend initializes, the conftest/dryrun bootstrap), so the
    serving number survives TPU-tunnel outages alongside host_replay_2m /
    host_dedup_2m — and the hard timeout keeps a wedged child from eating
    the bench line.
    """
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # sitecustomize TPU-plugin gate
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [
        sys.executable, os.path.join(repo, "tools", "loadgen.py"),
        "--platform", "cpu",
        "--clients", str(clients),
        "--duration", str(duration),
        "--network", network,
        "--max-batch", str(max_batch),
        "--seq-seconds", str(min(3.0, duration)),
        "--low-qps-requests", "10",
    ]
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout_s,
        env=env, cwd=repo,
    )
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip()[-400:]
        raise RuntimeError(f"loadgen rc={proc.returncode}: {tail}")
    r = json.loads(proc.stdout.strip().splitlines()[-1])
    return {
        "sequential_qps": r["sequential"]["qps"],
        "batched_qps": r["concurrent"]["qps"],
        "speedup": r["speedup"],
        "clients": r["config"]["clients"],
        "max_batch": r["config"]["max_batch"],
        "network": r["config"]["network"],
        "p50_ms": r["concurrent"]["latency"].get("p50_ms"),
        "p99_ms": r["concurrent"]["latency"].get("p99_ms"),
        "batch_hist": r["concurrent"]["batch_hist"],
        "reloads": r["reloads"]["observed"],
        "checks": r["checks"],
        "note": (
            "CPU-pinned subprocess (host-only: survives TPU-tunnel "
            "outages); closed-loop clients vs batch-1 sequential baseline"
        ),
    }


def _serving_net_bench(clients_per_replica: int = 4, duration: float = 6.0,
                       network: str = "mlp", env: str = "random:84x84x1",
                       replica_counts: str = "1,2",
                       timeout_s: float = 560.0) -> dict:
    """``serving_net``: the socket serving tier's scale-out point —
    tools/loadgen.py ``--compare-replicas`` in a CPU-pinned subprocess
    (the ``serving_qps`` isolation pattern: the child forces
    ``jax_platforms=cpu``, a hard timeout keeps a wedged fleet from
    eating the bench line).  One fleet per width at matched per-replica
    offered load, over real sockets through the health-aware router,
    with hot param reloads fanned out as page-deltas mid-window."""
    repo = os.path.dirname(os.path.abspath(__file__))
    env_vars = dict(os.environ)
    env_vars["JAX_PLATFORMS"] = "cpu"
    env_vars.pop("PALLAS_AXON_POOL_IPS", None)  # sitecustomize plugin gate
    env_vars["PYTHONPATH"] = repo + os.pathsep + env_vars.get(
        "PYTHONPATH", ""
    )
    cmd = [
        sys.executable, os.path.join(repo, "tools", "loadgen.py"),
        "--platform", "cpu",
        "--compare-replicas", replica_counts,
        "--clients", str(clients_per_replica),
        "--duration", str(duration),
        "--network", network,
        "--env", env,
        "--reloads", "2",
    ]
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout_s,
        env=env_vars, cwd=repo,
    )
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip()[-400:]
        raise RuntimeError(f"socket loadgen rc={proc.returncode}: {tail}")
    r = json.loads(proc.stdout.strip().splitlines()[-1])
    runs = {
        k: {
            "qps": v["qps"],
            "p50_ms": v["latency"]["p50_ms"],
            "p99_ms": v["latency"]["p99_ms"],
            "timeouts": v["timeouts"],
            "shed": v["shed"],
            "param_full_bytes": v["param_full_bytes"],
            "delta_bytes_max": v["delta_bytes_max"],
            "param_pushes": v["param"]["param_pushes"],
        }
        for k, v in r["runs"].items()
    }
    return {
        "methodology": r["methodology"],
        "runs": runs,
        "scaleout": r["scaleout"],
        "checks": r["checks"],
        "note": (
            "CPU-pinned subprocess fleet (replica children are separate "
            "processes on this host); matched per-replica closed-loop "
            "load, real sockets through the router, delta param fan-out"
        ),
    }


def _xp_transport_bench(workers=(4, 16, 64), seconds: float = 3.0,
                        rows: int = 64, obs_shape=(84, 84, 1),
                        barrage_rounds: int = 2) -> dict:
    """``xp_transport``: the actor→learner chunk path in isolation — shm
    ring (runtime/shm_ring.py) vs the pre-ring pickle-over-mp.Queue — at
    three fleet widths, plus the SIGKILL barrage proving zero
    fully-committed chunks are lost across random mid-stream kills.

    Host-only by construction (tools/xp_transport.py loads shm_ring.py by
    file path; no process imports jax), so the section survives TPU-tunnel
    outages alongside host_replay_2m / host_dedup_2m.
    """
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tools.xp_transport import run_sigkill_barrage, run_transport_bench

    out = run_transport_bench(list(workers), seconds=seconds, rows=rows,
                              obs_shape=tuple(obs_shape))
    out["sigkill_barrage"] = run_sigkill_barrage(
        workers=min(4, max(workers)), rounds=barrage_rounds, rows=rows,
        obs_shape=tuple(obs_shape),
    )
    for p in out["points"]:
        p["shm_beats_queue_2x"] = bool(p["speedup"] >= 2.0)
    return out


def _xp_net_bench(workers=(4, 16, 64), seconds: float = 3.0,
                  rows: int = 64, obs_shape=(84, 84, 1)) -> dict:
    """``xp_net``: shm ring vs the TCP transport backend on loopback
    (ISSUE 8), now with the wire-efficiency legs alongside (ISSUE 10) —
    plain v1 frames vs coalesce+dedup vs coalesce+dedup+zlib, all
    carrying identical APXT records built from trajectory-shaped chunks
    (matched settings), with wire-vs-logical bytes/transition per leg.
    Loopback is the cross-host transport's upper bound: it pays the
    framing, crc, kernel socket path and per-frame copies, but no wire
    latency.

    Host-only by construction (tools/xp_transport.py loads shm_ring.py
    and net.py by file path; no process imports jax), so the section
    survives TPU-tunnel outages alongside xp_transport.
    """
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from tools.xp_transport import run_net_bench

    return run_net_bench(list(workers), seconds=seconds, rows=rows,
                         obs_shape=tuple(obs_shape))


def _pipeline_overlap_bench(steps: int = 6400, steps_per_call: int = 64,
                            sync_every: int = 1024,
                            timeout_s: float = 900.0) -> dict:
    """``pipeline_overlap``: the overlapped dispatch pipeline (ISSUE 5)
    swept over depth 1 (strict) / 2 / 4 on one fused workload —
    host-sync counts, steps/s delta, and the device-idle (overlap gap)
    percentiles.

    Runs tools/pipeline_smoke.py --bench in a CPU-pinned subprocess
    (host-only by construction: the child forces jax_platforms=cpu, so
    the section survives TPU-tunnel outages alongside host_replay_2m —
    and the hard timeout keeps a wedged child from eating the bench
    line, the outage-proof subprocess probe discipline).  Sync-count and
    overlap accounting are platform-independent; the ~140 ms/sync charge
    they amortize is chip-side (PROFILE.md round-6).
    """
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)  # sitecustomize TPU-plugin gate
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [
        sys.executable, os.path.join(repo, "tools", "pipeline_smoke.py"),
        "--bench",
        "--steps", str(steps),
        "--steps-per-call", str(steps_per_call),
        "--sync-every", str(sync_every),
    ]
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout_s,
        env=env, cwd=repo,
    )
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip()[-400:]
        raise RuntimeError(f"pipeline_smoke rc={proc.returncode}: {tail}")
    out = json.loads(proc.stdout.strip().splitlines()[-1])["pipeline_overlap"]
    out["sync_reduction_10x_at_depth4"] = bool(
        out.get("sync_reduction_x_depth4", 0) >= 10.0
    )
    return out


def _make_chunks(rng, n, m, obs_shape, num_actions):
    import jax
    import jax.numpy as jnp

    from ape_x_dqn_tpu.types import NStepTransition

    chunks = []
    for _ in range(n):
        chunks.append(
            jax.device_put(
                NStepTransition(
                    obs=jnp.asarray(
                        rng.integers(0, 255, (m, *obs_shape), dtype=np.uint8)
                    ),
                    action=jnp.asarray(
                        rng.integers(0, num_actions, (m,), dtype=np.int32)
                    ),
                    reward=jnp.asarray(rng.normal(size=(m,)).astype(np.float32)),
                    discount=jnp.full((m,), 0.97, jnp.float32),
                    next_obs=jnp.asarray(
                        rng.integers(0, 255, (m, *obs_shape), dtype=np.uint8)
                    ),
                )
            )
        )
    return chunks


def _validate_samplers(rng) -> dict:
    """Run all three sampler spellings on the real chip at 2M slots and
    report agreement with an exact float64 host oracle (VERDICT item 3)."""
    import jax.numpy as jnp

    from ape_x_dqn_tpu.ops.pallas.sampling import (
        _pallas_sample,
        _two_level_sample,
        _xla_sample,
    )

    C, B = 1 << 21, 32
    p_np = rng.random(C, dtype=np.float32) + 1e-3
    p = jnp.asarray(p_np)
    total = float(np.sum(p_np.astype(np.float64)))
    t_np = (rng.random(B) * total).astype(np.float32)
    t = jnp.asarray(t_np)
    cdf64 = np.cumsum(p_np.astype(np.float64))
    exact = np.searchsorted(cdf64, t_np.astype(np.float64), side="right")

    out = {}
    for name, fn in (
        ("two_level", _two_level_sample),
        ("pallas", _pallas_sample),
        ("xla", _xla_sample),
    ):
        idx = np.asarray(fn(p, t))
        # float32 accumulation-order shifts boundaries by a few leaves out
        # of 2M — mass-proportionally immaterial; >64 would be a logic bug.
        # No standalone timing: per-call dispatch on this platform costs a
        # program-dependent fixed ~2-120 ms that swamps any µs-scale kernel
        # (measured: scan iteration count doesn't change wall time).  The
        # sampler's real cost is part of the fused us_per_step headline.
        max_err = int(np.max(np.abs(idx - exact)))
        assert max_err <= 64, f"{name} sampler diverged from f64 oracle: {max_err}"
        out[name] = {"max_leaf_err_2m": max_err}
    return out


def _median_pipeline(trials: int, **kw) -> dict:
    """Run _pipeline_bench ``trials`` times; report the median run (by the
    steady-state window rate) plus per-trial numbers and spread.  Round-4
    verdict item 3: single trials on a contended 1-core VM are coin flips
    (546 vs 1,024 steps/s for the same config across captures) — claims
    must come from a median with the spread shown."""
    runs = [_pipeline_bench(**kw) for _ in range(trials)]
    key = "window_steps_per_sec"
    vals = sorted(float(r[key]) for r in runs)
    med = vals[len(vals) // 2]
    rep = dict(next(r for r in runs if float(r[key]) == med))
    rep["trials"] = [
        {k: r[k] for k in ("learner_steps_per_sec", "window_steps_per_sec",
                           "actor_fps", "window_actor_fps", "wall_s")}
        for r in runs
    ]
    rep["median_window_steps_per_sec"] = med
    rep["spread_pct"] = round(
        (vals[-1] - vals[0]) / max(med, 1e-9) * 100.0, 1
    )
    return rep


def _pipeline_bench(learner_steps: int = 20_000, steps_per_call: int = 1024,
                    publish_every: int = 4000, num_actors: int = 512,
                    actor_mode: str = "thread", num_workers: int = 4,
                    min_replay: int = 20_000, worker_nice: int = 10,
                    ingest_block: int = 2048, dedup: bool = False) -> dict:
    """End-to-end async pipeline on the real chip (VERDICT r2 item 2): actors
    + device infeed + the fused HBM learner — reports BOTH north-star
    metrics (learner steps/s AND actor FPS) from the same run.

    ``actor_mode="thread"`` puts the actor fleet's batched policy forwards
    on the TPU, CONTENDING with the learner for the one device queue (the
    round-3 result: every host sync charges ~140-240 ms to the next
    dispatch, so the two stages serialize).  ``actor_mode="process"`` is
    the designed mitigation (round-3 verdict item 2): worker processes do
    CPU-only inference (runtime/process_actors.py), the learner owns the
    device alone, and learner steps/s should recover toward the solo
    figure — actor FPS is then bounded by host cores (this driver VM has
    ONE), not the framework."""
    from ape_x_dqn_tpu.config import ApexConfig
    from ape_x_dqn_tpu.runtime.async_pipeline import AsyncPipeline
    from ape_x_dqn_tpu.utils.metrics import MetricLogger

    cfg = ApexConfig()
    cfg.network = "conv"
    cfg.env.name = "random:84x84x1"
    cfg.actor.num_actors = num_actors   # one fleet: batched policy steps
    cfg.actor.T = 10_000_000
    cfg.actor.flush_every = 16
    cfg.actor.sync_every = 500
    cfg.actor.mode = actor_mode
    cfg.actor.num_workers = num_workers
    # Keep the learner's dispatch thread scheduled ahead of worker CPU
    # inference — this driver VM has one core (see actor.worker_nice).
    cfg.actor.worker_nice = worker_nice
    cfg.learner.device_replay = True
    cfg.replay.dedup = dedup
    if actor_mode == "process":
        # Fewer, larger host->device ingest dispatches (~35 ms each
        # through this tunnel).
        cfg.learner.ingest_block = ingest_block
    cfg.learner.sample_ahead = True
    cfg.learner.steps_per_call = steps_per_call
    # Publish cadence: each publish is a full param device_get through the
    # tunnel (~13 MB) that also drains the device queue — at the reference's
    # per-step-minded default (10) it would fire once per fused call and
    # dominate the learner's wall clock.
    cfg.learner.publish_every = publish_every
    cfg.learner.min_replay_mem_size = min_replay
    cfg.learner.optimizer = "rmsprop"
    cfg.learner.max_grad_norm = None
    cfg.learner.second_moment_dtype = "bfloat16"
    cfg.learner.target_dtype = "bfloat16"
    cfg.learner.total_steps = learner_steps
    cfg.replay.capacity = 100_000
    devnull = open(os.devnull, "w")
    logger = MetricLogger(stream=devnull)
    pipe = AsyncPipeline(cfg, logger=logger, log_every=1_000_000)
    t0 = time.perf_counter()
    try:
        result = pipe.run(learner_steps=learner_steps, warmup_timeout=300.0)
    finally:
        wall = time.perf_counter() - t0
        devnull.close()
    assert np.isfinite(result["learner/loss"]), result
    return {
        "learner_steps_per_sec": round(result["step"] / wall, 1),
        "actor_fps": round(result["actor_steps"] / wall, 1),
        "learner_steps": result["step"],
        "actor_steps": result["actor_steps"],
        "wall_s": round(wall, 1),
        "window_steps_per_sec": result["steps_per_sec"],
        "window_actor_fps": result["actor_fps"],
        "config": {
            "num_actors": cfg.actor.num_actors,
            "actor_mode": actor_mode,
            "dedup": dedup,
            "num_workers": num_workers if actor_mode == "process" else None,
            "env": cfg.env.name,
            "steps_per_call": cfg.learner.steps_per_call,
            "publish_every": cfg.learner.publish_every,
            "min_replay": min_replay,
            "note": (
                "whole-run averages incl. warmup and compiles; "
                "window_* are the final 30s sliding-window rates "
                "(the steady-state numbers)"
            ),
        },
    }


def _actor_solo_bench(fleet_steps: int = 192, num_actors: int = 512) -> dict:
    """Uncontended actor FPS: one batched fleet stepping RandomFrameEnv with
    jitted policy forwards and the full n-step/priority emission path, no
    learner sharing the device — the actor-side capability ceiling."""
    import jax

    from ape_x_dqn_tpu.actors import ActorFleet, LocalParamSource
    from ape_x_dqn_tpu.envs import RandomFrameEnv
    from ape_x_dqn_tpu.models.dueling import build_network

    net = build_network("conv", 4)
    fleet = ActorFleet(
        [lambda: RandomFrameEnv((84, 84, 1), num_actions=4)] * num_actors,
        net, n_step=3, flush_every=16,
    )
    params = net.init(
        jax.random.PRNGKey(0), np.zeros((1, 84, 84, 1), np.uint8)
    )
    fleet.sync_params(LocalParamSource(params))
    fleet.collect(32)  # compile + warm
    t0 = time.perf_counter()
    chunks, _ = fleet.collect(fleet_steps)
    dt = time.perf_counter() - t0
    emitted = sum(c.transitions.action.shape[0] for c in chunks)
    return {
        "actor_fps": round(fleet_steps * num_actors / dt, 1),
        "fleet_steps_per_sec": round(fleet_steps / dt, 1),
        "num_actors": num_actors,
        "transitions_emitted": emitted,
    }


def _host_replay_bench(capacity: int = 2_000_000, iters: int = 2000) -> dict:
    """Host sum-tree replay throughput at paper scale (SURVEY §7 hard part
    #1: 'the central sum-tree is the only serialized component in Ape-X').
    Measures the learner-facing loop — stratified sample(32) + priority
    restamp — and the actor-facing batched add, on the C++ core."""
    from ape_x_dqn_tpu.replay import PrioritizedReplay
    from ape_x_dqn_tpu.types import NStepTransition

    rng = np.random.default_rng(0)
    obs_shape = (84, 84, 1)
    rep = PrioritizedReplay(capacity, obs_shape)
    M = 4096
    chunk = NStepTransition(
        obs=rng.integers(0, 255, (M, *obs_shape), dtype=np.uint8),
        action=rng.integers(0, 4, (M,), dtype=np.int32),
        reward=rng.normal(size=(M,)).astype(np.float32),
        discount=np.full((M,), 0.97, np.float32),
        next_obs=rng.integers(0, 255, (M, *obs_shape), dtype=np.uint8),
    )
    prio = (np.abs(rng.normal(size=(M,))) + 0.1).astype(np.float32)
    # Occupancy: half the ring (~14 GB of touched frame pages at 2M slots —
    # sized for the 125 GB driver host; shrink --capacity on small VMs).
    n_prefill = max(1, capacity // (2 * M))
    for _ in range(n_prefill):
        rep.add(prio, chunk)
    t0 = time.perf_counter()
    srng = np.random.default_rng(1)
    for _ in range(iters):
        batch = rep.sample(32, rng=srng)
        rep.update_priorities(
            batch.indices, np.abs(rng.normal(size=32)) + 0.1
        )
    dt = time.perf_counter() - t0
    t1 = time.perf_counter()
    for _ in range(16):
        rep.add(prio, chunk)
    dt_add = time.perf_counter() - t1
    # Tree-only (no frame gather): separates the O(log N) structure cost
    # from the host's frame-copy bandwidth, which dominates on weak VMs.
    t2 = time.perf_counter()
    for _ in range(iters):
        idx = rep._tree.sample_stratified(32, srng)
        rep._tree.set(idx, np.abs(rng.normal(size=32)) + 0.1)
    dt_tree = time.perf_counter() - t2
    tree = type(rep._tree).__name__
    return {
        "sample_update_pairs_per_sec": round(iters / dt, 1),
        "samples_per_sec": round(iters * 32 / dt),
        "tree_only_pairs_per_sec": round(iters / dt_tree, 1),
        "add_transitions_per_sec": round(16 * M / dt_add),
        "capacity": capacity,
        "occupancy": min(n_prefill * M, capacity),
        "sum_tree": tree,
        "note": (
            "single-core host VM; frame memcpy dominates the full-path "
            "numbers — tree_only is the sum-tree's own ceiling here"
        ),
    }


def _host_dedup_bench(capacity: int = 2_000_000, iters: int = 2000,
                      n_stripes: int = 1) -> dict:
    """Paper-scale HOST path on the native C++ dedup core (VERDICT r4 item
    1b): one GIL-released call per stage — stratified sample + IS weights
    + both frame gathers fused (rc_sample), ring write + priority set +
    liveness sweep fused (rc_add) — over a THP-backed frame ring storing
    each frame once (2M slots ≈ 17.6 GB at ratio 1.25 vs the double-store's
    28 GB)."""
    from ape_x_dqn_tpu.replay.native_dedup import (
        NativeDedupReplay,
        native_dedup_available,
        native_dedup_error,
    )
    from ape_x_dqn_tpu.types import DedupChunk

    if not native_dedup_available():
        return {"skipped": f"native core unavailable: {native_dedup_error()}"}
    rng = np.random.default_rng(0)
    obs_shape = (84, 84, 1)
    rep = NativeDedupReplay(capacity, obs_shape, frame_ratio=1.25,
                            n_stripes=n_stripes)
    M = 4096  # transitions per chunk over M+1 fresh frames (dedup stream)
    frames = rng.integers(0, 255, (M + 1, *obs_shape), dtype=np.uint8)
    chunk_proto = dict(
        obs_ref=np.arange(M, dtype=np.int32),
        next_ref=np.arange(1, M + 1, dtype=np.int32),
        action=rng.integers(0, 4, M).astype(np.int32),
        reward=rng.normal(size=M).astype(np.float32),
        discount=np.full(M, 0.97, np.float32),
        prev_frames=M + 1,
    )
    prio = (np.abs(rng.normal(size=M)) + 0.1).astype(np.float32)
    n_prefill = max(1, capacity // (2 * M))
    for i in range(n_prefill):
        rep.add(prio, DedupChunk(frames=frames, source=1, chunk_seq=i,
                                 **chunk_proto))
    t0 = time.perf_counter()
    srng = np.random.default_rng(1)
    B = 32 if n_stripes == 1 else 32 - 32 % n_stripes
    for _ in range(iters):
        batch = rep.sample(B, rng=srng)
        rep.update_priorities(
            batch.indices, np.abs(rng.normal(size=B)) + 0.1
        )
    dt = time.perf_counter() - t0
    t1 = time.perf_counter()
    for i in range(16):
        rep.add(prio, DedupChunk(frames=frames, source=1,
                                 chunk_seq=n_prefill + i, **chunk_proto))
    dt_add = time.perf_counter() - t1
    return {
        "sample_update_pairs_per_sec": round(iters / dt, 1),
        "samples_per_sec": round(iters * B / dt),
        "add_transitions_per_sec": round(16 * M / dt_add),
        "capacity": capacity,
        "occupancy": min(n_prefill * M, capacity),
        "n_stripes": n_stripes,
        "frames_gb": round(rep.frames_nbytes() / 1e9, 2),
        "note": (
            "fused C calls (GIL released), THP frame ring, frames stored "
            "once; compare host_replay_2m (python double-store)"
        ),
    }


def _replay_svc_bench(iters: int = 300, batch: int = 32,
                      capacity: int = 16_384, rows: int = 8_192,
                      timeout_s: float = 420.0) -> dict:
    """``replay_svc``: tools/replay_svc_bench.py in a CPU-pinned
    subprocess (the ``serving_qps`` isolation pattern) — RPC sample vs
    in-process sample at the Atari frame shape, with the codec-off /
    codec-zlib / codec-auto split (auto = backpressure-gated reply
    compression: it must price like off on an unloaded loopback, not
    like the always-zlib worst case) and the dedup wire economy on the
    add path (ROADMAP item 1's bench leg; committed:
    demos/replay_svc.json)."""
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "replay_svc_bench.py"),
         "--iters", str(iters), "--batch", str(batch),
         "--capacity", str(capacity), "--rows", str(rows)],
        capture_output=True, text=True, timeout=timeout_s, env=env,
        cwd=repo,
    )
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip()[-400:]
        raise RuntimeError(f"replay_svc_bench rc={proc.returncode}: {tail}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _central_inference_bench(widths: str = "4,16,64",
                             measure_s: float = 20.0,
                             ramp_timeout_s: float = 480.0,
                             skip_kill_leg: bool = False,
                             timeout_s: float = 2400.0) -> dict:
    """``central_inference``: tools/central_inference_bench.py in a
    CPU-pinned subprocess (the ``serving_qps`` isolation pattern —
    outage-proof, hard timeout) — env-steps/s of PARAMLESS workers
    (action selection through the serving tier's micro-batcher, SEED
    style) vs param-holding ones at 4/16/64 worker processes, matched
    config, plus round-trip percentiles, batch occupancy, the obs wire
    economy, and the replica-kill leg (the verify-gate smoke's verdict:
    zero torn / zero drops through a mid-run SIGKILL).  Committed:
    demos/central_inference.json (ROADMAP item 2's bench leg)."""
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [
        sys.executable,
        os.path.join(repo, "tools", "central_inference_bench.py"),
        "--widths", widths, "--measure-s", str(measure_s),
        "--ramp-timeout-s", str(ramp_timeout_s),
    ]
    if skip_kill_leg:
        cmd.append("--skip-kill-leg")
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout_s, env=env,
        cwd=repo,
    )
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip()[-400:]
        raise RuntimeError(
            f"central_inference_bench rc={proc.returncode}: {tail}"
        )
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _replay_tiered_bench(capacity: int = 200_000, iters: int = 1000,
                         hot_frac: float = 0.25,
                         workdir: str | None = None) -> dict:
    """Tiered replay vs in-core (ROADMAP item 6): a dedup replay whose
    frame footprint exceeds the hot budget (hot cap <= 25% of frames)
    sampling/updating at a sustained rate, the background evictor holding
    the budget while the learner-side loop faults what it samples — the
    capacity-beyond-DRAM measurement (committed: demos/replay_tiered.json,
    with the floor arithmetic in demos/README).  Host-only (no jax);
    native core when the toolchain allows, numpy twin otherwise."""
    import shutil
    import tempfile

    from ape_x_dqn_tpu.replay.dedup import DedupReplay
    from ape_x_dqn_tpu.replay.native_dedup import native_dedup_available
    from ape_x_dqn_tpu.replay.tiered import TierEvictor
    from ape_x_dqn_tpu.types import DedupChunk

    if native_dedup_available():
        from ape_x_dqn_tpu.replay.native_dedup import (
            NativeDedupReplay as Replay,
        )
        core = "native"
    else:
        Replay = DedupReplay
        core = "numpy"
    rng = np.random.default_rng(0)
    obs_shape = (84, 84, 1)
    frame_bytes = int(np.prod(obs_shape))
    ring_bytes = int(round(capacity * 1.25)) * frame_bytes
    hot_budget = int(ring_bytes * hot_frac)
    M = 4096
    frames = rng.integers(0, 255, (M + 1, *obs_shape), dtype=np.uint8)
    proto = dict(
        obs_ref=np.arange(M, dtype=np.int32),
        next_ref=np.arange(1, M + 1, dtype=np.int32),
        action=rng.integers(0, 4, M).astype(np.int32),
        reward=rng.normal(size=M).astype(np.float32),
        discount=np.full(M, 0.97, np.float32),
        prev_frames=M + 1,
    )
    prio = (np.abs(rng.normal(size=M)) + 0.1).astype(np.float32)
    n_prefill = max(1, capacity // (2 * M))

    def prefill(rep):
        for i in range(n_prefill):
            rep.add(prio, DedupChunk(frames=frames, source=1, chunk_seq=i,
                                     **proto))

    def run_loop(rep, skew=False):
        # skew=True restamps with lognormal priorities (heavy-tailed TD
        # errors — the realistic PER regime): sampling concentrates, the
        # LRU working set shrinks, fault rate drops.  skew=False is the
        # near-uniform worst case.
        if getattr(rep, "tier", None) is not None:
            # Steady-state methodology: write-back every dirty span's
            # record (keeping residency), then trim to the budget with
            # clean drops — the timed region starts with the hot tier AT
            # its cap and every record current, and measures the steady
            # sample/fault/clean-drop cycle rather than the one-time
            # spill of a cold-started ring.
            rep.tier_flush_dirty()
            while rep.tier_over_watermark():
                rep.spill_cold(max_spans=1024)
        srng = np.random.default_rng(1)
        urng = np.random.default_rng(2)

        def new_prio():
            if skew:
                return np.exp(
                    2.0 * urng.normal(size=32)
                ).astype(np.float32)
            return (np.abs(urng.normal(size=32)) + 0.1).astype(np.float32)

        for _ in range(min(128, iters // 4)):  # warmup (untimed)
            batch = rep.sample(32, rng=srng)
            rep.update_priorities(batch.indices, new_prio())
        t0 = time.perf_counter()
        for _ in range(iters):
            batch = rep.sample(32, rng=srng)
            rep.update_priorities(batch.indices, new_prio())
        return time.perf_counter() - t0

    # In-core baseline (tier off — the zero-cost-when-off configuration).
    rep = Replay(capacity, obs_shape, frame_ratio=1.25)
    prefill(rep)
    dt_incore = run_loop(rep)
    del rep
    # Tiered: hot cap at hot_frac of the ring, background evictor holding
    # it, the sample loop faulting what it draws.
    spill = workdir or tempfile.mkdtemp(prefix="apex-bench-tier-")
    # span_frames=2: obs/next of one transition are adjacent seqs, so a
    # 2-frame span serves both with minimal read amplification (the auto
    # 64 KiB spans fault ~4x more bytes per sampled row at this frame
    # size).
    rep = Replay(capacity, obs_shape, frame_ratio=1.25,
                 hot_frame_budget_bytes=hot_budget, spill_dir=spill,
                 spill_span_frames=2)
    evictor = TierEvictor(rep, poll_s=0.005)
    evictor.start()
    try:
        prefill(rep)
        dt_tiered = run_loop(rep)
        stats = rep.tier_stats()
        # Second point on the SAME warm replay: heavy-tailed priorities
        # (the realistic PER regime) — sampling concentrates, faults drop.
        dt_skew = run_loop(rep, skew=True)
        stats_skew = rep.tier_stats()
    finally:
        evictor.stop()
        del rep
        if workdir is None:
            shutil.rmtree(spill, ignore_errors=True)
    in_core_rate = iters / dt_incore
    tiered_rate = iters / dt_tiered
    skew_rate = iters / dt_skew
    return {
        "tiered_pairs_per_sec_skewed": round(skew_rate, 1),
        "slowdown_x_skewed": round(in_core_rate / max(skew_rate, 1e-9), 2),
        "fault_reads_skewed_phase": (
            stats_skew["fault_reads"] - stats["fault_reads"]
        ),
        "core": core,
        "capacity": capacity,
        "occupancy": min(n_prefill * M, capacity),
        "ring_gb": round(ring_bytes / 1e9, 3),
        "hot_budget_gb": round(hot_budget / 1e9, 3),
        "hot_frac": hot_frac,
        "in_core_pairs_per_sec": round(in_core_rate, 1),
        "tiered_pairs_per_sec": round(tiered_rate, 1),
        "slowdown_x": round(in_core_rate / max(tiered_rate, 1e-9), 2),
        "spill_writes": stats["spill_writes"],
        "spilled_gb": round(stats["spilled_bytes"] / 1e9, 3),
        "fault_reads": stats["fault_reads"],
        "fault_gb": round(stats["fault_bytes"] / 1e9, 3),
        "fault_ms": stats["fault_ms"],
        "hot_bytes_end": stats["hot_bytes"],
        "note": (
            "sample(32)+update pairs; tier holds hot <= "
            f"{int(hot_frac * 100)}% of frames (evictor thread), sample "
            "path faults cold spans through CRC-verified reads; "
            "bit-exactness pinned by tests/test_tiered_replay.py"
        ),
    }


def _checkpoint_stall_bench(capacity: int = 2_000_000,
                            interval_rows: int = 65_536,
                            deltas: int = 3,
                            workdir: str | None = None) -> dict:
    """Learner-visible checkpoint stall: synchronous full-write vs the
    incremental async subsystem (utils/checkpoint_inc), at the 2M-slot host
    DEDUP layout (config3's ~17.6 GB frame ring, PROFILE.md round 5 — the
    buffer whose inline np.savez was minutes of learner dead air).

    Host-only (native C++ dedup core, no jax).  Two measurements:
      * ``full_sync``: one inline full snapshot+write on the caller thread
        — the status-quo save_checkpoint replay leg, same wire format.
      * ``incremental``: async saves at a fixed ingest interval; the
        learner-visible stall is just ``save()`` (dirty-span memcpy +
        enqueue), the write lands on the writer thread.  A half-interval
        delta shows bytes ∝ interval, not capacity.
    """
    import shutil
    import tempfile

    from ape_x_dqn_tpu.replay.native_dedup import (
        NativeDedupReplay,
        native_dedup_available,
        native_dedup_error,
    )
    from ape_x_dqn_tpu.types import DedupChunk
    from ape_x_dqn_tpu.utils.checkpoint_inc import IncrementalCheckpointer

    if not native_dedup_available():
        return {"skipped": f"native core unavailable: {native_dedup_error()}"}
    rng = np.random.default_rng(0)
    obs_shape = (84, 84, 1)
    rep = NativeDedupReplay(capacity, obs_shape, frame_ratio=1.25)
    M = 4096  # transitions per chunk over M+1 fresh frames (dedup stream)
    frames = rng.integers(0, 255, (M + 1, *obs_shape), dtype=np.uint8)
    chunk_proto = dict(
        obs_ref=np.arange(M, dtype=np.int32),
        next_ref=np.arange(1, M + 1, dtype=np.int32),
        action=rng.integers(0, 4, M).astype(np.int32),
        reward=rng.normal(size=M).astype(np.float32),
        discount=np.full(M, 0.97, np.float32),
        prev_frames=M + 1,
    )
    prio = (np.abs(rng.normal(size=M)) + 0.1).astype(np.float32)
    seq = 0

    def ingest(rows: int) -> None:
        nonlocal seq
        for _ in range(max(1, rows // M)):
            rep.add(prio, DedupChunk(frames=frames, source=1, chunk_seq=seq,
                                     **chunk_proto))
            seq += 1

    def churn(iters: int = 32) -> None:
        # Learner-shaped priority restamps between checkpoints — the
        # sparse half of a delta.
        srng = np.random.default_rng(seq)
        for _ in range(iters):
            batch = rep.sample(32, rng=srng)
            rep.update_priorities(
                batch.indices, np.abs(srng.normal(size=32)) + 0.1
            )

    ingest(capacity // 2)  # half occupancy, like host_dedup_2m
    root = tempfile.mkdtemp(prefix="ckpt_stall_", dir=workdir)
    try:
        # -- synchronous full write (the path being replaced) -------------
        full = IncrementalCheckpointer(os.path.join(root, "full"), rep,
                                       sync=True)
        t0 = time.perf_counter()
        full.save(0, force_base=True)
        full_stall_ms = (time.perf_counter() - t0) * 1e3
        full_bytes = full.stats()["last_chunk_bytes"]
        shutil.rmtree(os.path.join(root, "full"))  # reclaim before leg 2

        # -- incremental async -------------------------------------------
        ck = IncrementalCheckpointer(os.path.join(root, "inc"), rep,
                                     base_every=64)
        ck.save(0)        # generation base (async, amortized over the run)
        ck.flush()
        base_bytes = ck.stats()["last_chunk_bytes"]
        stalls, delta_bytes = [], []
        for k in range(deltas):
            ingest(interval_rows)
            churn()
            t0 = time.perf_counter()
            assert ck.save(k + 1)
            stalls.append((time.perf_counter() - t0) * 1e3)
            ck.flush()  # outside the stall: the writer's time, not the
            #             learner's (flush here only so last_chunk_bytes
            #             and the next save's backpressure are exact)
            delta_bytes.append(ck.stats()["last_chunk_bytes"])
        ingest(interval_rows // 2)
        churn()
        t0 = time.perf_counter()
        assert ck.save(deltas + 1)
        half_stall_ms = (time.perf_counter() - t0) * 1e3
        ck.flush()
        half_bytes = ck.stats()["last_chunk_bytes"]
        ck.close()
    finally:
        shutil.rmtree(root, ignore_errors=True)

    mean_stall = sum(stalls) / len(stalls)
    mean_bytes = sum(delta_bytes) / len(delta_bytes)
    return {
        "capacity": capacity,
        "occupancy": rep.size(),
        "frames_gb": round(rep.frames_nbytes() / 1e9, 2),
        "interval_rows": interval_rows,
        "full_sync": {
            "stall_ms": round(full_stall_ms, 1),
            "bytes": int(full_bytes),
        },
        "incremental": {
            "base_bytes": int(base_bytes),
            "delta_stall_ms": [round(s, 1) for s in stalls],
            "delta_stall_ms_mean": round(mean_stall, 1),
            "delta_bytes": [int(b) for b in delta_bytes],
            "half_interval_stall_ms": round(half_stall_ms, 1),
            "half_interval_bytes": int(half_bytes),
        },
        "stall_reduction_x": round(full_stall_ms / max(mean_stall, 1e-3), 1),
        "delta_vs_full_bytes_x": round(full_bytes / max(mean_bytes, 1), 1),
        "half_over_full_interval_bytes": round(half_bytes / mean_bytes, 3),
        "note": (
            "learner-visible stall = time inside save(); the incremental "
            "save's IO happens on the writer thread.  half_over_full_"
            "interval_bytes ~ 0.5 demonstrates delta bytes proportional "
            "to the checkpoint interval, not the ring capacity"
        ),
    }


def _dedup_fused_bench(args, jnp, jax) -> dict:
    """Single-chip fused learner on the DEDUP HBM ring at the headline
    workload — the per-step cost of the ref indirection vs the
    double-store headline (expected ~neutral: same gathered bytes, half
    the ring HBM)."""
    from ape_x_dqn_tpu.learner.train_step import (
        build_train_step,
        init_train_state,
        make_optimizer,
    )
    from ape_x_dqn_tpu.models.dueling import build_network
    from ape_x_dqn_tpu.replay.device_dedup import (
        build_dedup_fused_learn_step,
        dedup_device_add_frames,
        dedup_device_add_transitions,
        init_dedup_device_replay,
    )

    B, K, C = args.batch_size, args.steps_per_call, args.capacity
    obs_shape, A, M = (84, 84, 1), 4, 256
    target_sync_freq = 2500 - 2500 % K if K <= 2500 else K
    net = build_network("conv", A)
    opt = make_optimizer(
        "rmsprop", max_grad_norm=None, second_moment_dtype=jnp.bfloat16
    )
    step_fn = build_train_step(net, opt, sync_in_step=False, jit=False)
    fused = build_dedup_fused_learn_step(
        step_fn, B, steps_per_call=K, target_sync_freq=target_sync_freq,
        sample_ahead=not args.strict_per,
    )
    replay = init_dedup_device_replay(C, obs_shape, frame_ratio=1.25)
    Q = replay.seq_modulus
    add_f = jax.jit(dedup_device_add_frames, donate_argnums=(0,))
    add_t = jax.jit(dedup_device_add_transitions, donate_argnums=(0,))
    rng = np.random.default_rng(0)
    frames = jax.device_put(jnp.asarray(
        rng.integers(0, 255, (M + 1, *obs_shape), dtype=np.uint8)
    ))
    meta = [
        jax.device_put(jnp.asarray(a)) for a in (
            rng.integers(0, A, (M,)).astype(np.int32),
            rng.normal(size=(M,)).astype(np.float32),
            np.full((M,), 0.97, np.float32),
            np.ones((M,), np.float32),
        )
    ]
    fbase = 0
    for _ in range(40):
        oref = jnp.asarray((fbase + np.arange(M)) % Q, jnp.int32)
        nref = jnp.asarray((fbase + 1 + np.arange(M)) % Q, jnp.int32)
        replay = add_f(replay, frames)
        replay = add_t(replay, oref, nref, *meta)
        fbase += M + 1
    state = init_train_state(
        net, opt, jax.random.PRNGKey(0),
        jnp.zeros((1, *obs_shape), jnp.uint8), target_dtype=jnp.bfloat16,
    )
    key = jax.random.PRNGKey(1)
    for _ in range(2):
        key, sub = jax.random.split(key)
        state, replay, metrics = fused(state, replay, 0.4, sub)
    _ = np.asarray(metrics.loss)
    calls = args.timed_calls
    t0 = time.perf_counter()
    for _ in range(calls):
        key, sub = jax.random.split(key)
        state, replay, metrics = fused(state, replay, 0.4, sub)
    final_loss = np.asarray(metrics.loss)
    dt = time.perf_counter() - t0
    assert np.all(np.isfinite(final_loss)), "non-finite loss in dedup bench"
    rate = calls * K / dt
    return {
        "learner_steps_per_sec": round(rate, 1),
        "us_per_step": round(dt / (calls * K) * 1e6, 1),
        "hbm_frames_mb": round(replay.frames.nbytes / 1e6, 1),
        "double_store_frames_mb": round(
            2 * C * int(np.prod(obs_shape)) / 1e6, 1
        ),
        "config": {"batch_size": B, "steps_per_call": K, "capacity": C,
                   "frame_ratio": 1.25,
                   "sample_ahead": not args.strict_per},
    }


def _fused_headline_bench(args) -> dict:
    """The on-chip headline: fused HBM-replay learner steps/s (moved out of
    main so it runs inside fault isolation — VERDICT round-5 item 1: a
    backend failure here must cost this section, not the bench line)."""
    import jax
    import jax.numpy as jnp

    from ape_x_dqn_tpu.learner.train_step import (
        build_train_step,
        init_train_state,
        make_optimizer,
        with_float32_master,
    )
    from ape_x_dqn_tpu.models.dueling import build_network
    from ape_x_dqn_tpu.replay.device import (
        build_fused_learn_step,
        device_replay_add,
        init_device_replay,
    )

    B, K, C = args.batch_size, args.steps_per_call, args.capacity
    obs_shape, A, M = (84, 84, 1), 4, 256
    target_sync_freq = 2500 - 2500 % K if K <= 2500 else K  # multiple of K

    param_dtype = jnp.bfloat16 if args.param_dtype == "bfloat16" else jnp.float32
    net = build_network("conv", A, param_dtype=param_dtype)
    # Reference-parity RMSProp with the HBM-traffic knobs: no global-norm
    # clip (the reference has none), bfloat16 second moment + target net.
    # Params default to float32 (bf16+f32-master measured perf-neutral on
    # this chip — PROFILE.md round-4 update).
    opt = make_optimizer(
        "rmsprop", max_grad_norm=None, second_moment_dtype=jnp.bfloat16
    )
    if args.param_dtype == "bfloat16":
        opt = with_float32_master(opt)
    step_fn = build_train_step(net, opt, sync_in_step=False, jit=False)
    fused = build_fused_learn_step(
        step_fn, B, steps_per_call=K, target_sync_freq=target_sync_freq,
        sample_ahead=not args.strict_per,
    )

    rng = np.random.default_rng(0)
    chunks = _make_chunks(rng, 4, M, obs_shape, A)
    prio = jax.device_put(jnp.ones((M,), jnp.float32))

    replay = init_device_replay(C, obs_shape)
    add = jax.jit(device_replay_add, donate_argnums=(0,))
    for i in range(40):  # prefill past min_replay_size
        replay = add(replay, chunks[i % len(chunks)], prio)
    state = init_train_state(
        net,
        opt,
        jax.random.PRNGKey(0),
        jnp.zeros((1, *obs_shape), jnp.uint8),
        target_dtype=jnp.bfloat16,
    )

    key = jax.random.PRNGKey(1)
    for i in range(2):  # compile + steady-state warmup
        key, sub = jax.random.split(key)
        state, replay, metrics = fused(
            state, replay, chunks[i % len(chunks)], prio, 0.4, sub
        )
    _ = np.asarray(metrics.loss)

    calls = args.timed_calls
    t0 = time.perf_counter()
    for i in range(calls):
        key, sub = jax.random.split(key)
        state, replay, metrics = fused(
            state, replay, chunks[i % len(chunks)], prio, 0.4, sub
        )
    final_loss = np.asarray(metrics.loss)  # serial chain forces all calls
    dt = time.perf_counter() - t0
    assert np.all(np.isfinite(final_loss)), "non-finite loss in bench"

    rate = calls * K / dt
    return {
        "learner_steps_per_sec": round(rate, 1),
        "us_per_step": round(dt / (calls * K) * 1e6, 1),
        "samples_per_sec": round(rate * B),
        "config": {
            "batch_size": B,
            "steps_per_call": K,
            "capacity": C,
            "sampler": "two_level",
            "sample_ahead": not args.strict_per,
            "second_moment_dtype": "bfloat16",
            "target_dtype": "bfloat16",
            "param_dtype": args.param_dtype,
            "chip": jax.devices()[0].device_kind,
        },
        "note": (
            "honest forcing via host transfer; r01's 7337.8 used "
            "block_until_ready which is a no-op on this platform"
        ),
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps-per-call", type=int, default=2048)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--capacity", type=int, default=100_000)
    parser.add_argument("--timed-calls", type=int, default=8)
    parser.add_argument(
        "--strict-per", action="store_true",
        help="sequential PER (sample/restamp every step in-scan) instead of "
        "the batched sample-ahead mode (device_replay_sample_many)",
    )
    parser.add_argument(
        "--param-dtype", default="float32", choices=("bfloat16", "float32"),
        help="network param storage dtype (bfloat16 pairs with a float32 "
        "master copy in the optimizer — train_step.with_float32_master). "
        "Measured round 4: perf-neutral on this v5e (228.7 vs 221.5 "
        "µs/step) — the halved param reads are offset by the master "
        "copy's optimizer traffic; see PROFILE.md round-4 update.",
    )
    parser.add_argument(
        "--skip-sampler-validation", action="store_true",
        help="skip the 2M-slot sampler parity check (saves ~30s)",
    )
    parser.add_argument(
        "--skip-pipeline", action="store_true",
        help="skip the end-to-end async-pipeline run (actors + infeed + "
        "fused learner contending on the chip; ~90s)",
    )
    parser.add_argument("--pipeline-steps", type=int, default=16_384)
    parser.add_argument(
        "--pipeline-trials", type=int, default=3,
        help="trials per pipeline mode; the report carries the median run "
        "+ per-trial numbers + spread (single trials on this contended "
        "1-core VM are coin flips — round-4 verdict item 3)",
    )
    parser.add_argument(
        "--skip-host-dedup", action="store_true",
        help="skip the 2M native dedup host-replay bench (~17.6 GB RAM)",
    )
    parser.add_argument(
        "--host-replay-capacity", type=int, default=2_000_000,
        help="slots for the host sum-tree replay bench; NB the raw frame "
        "stores preallocate ~14 MB per 1000 slots (28 GB at the 2M "
        "default) — shrink on small-RAM machines",
    )
    parser.add_argument(
        "--probe-timeout", type=float, default=60.0,
        help="hard timeout (s) for the subprocess backend probe; a dead/"
        "hung tunnel flips the run to host-only sections + "
        "platform_outage=true instead of losing the bench line",
    )
    parser.add_argument("--skip-serving", action="store_true",
                        help="skip the serving_qps loadgen section")
    parser.add_argument("--serving-clients", type=int, default=32)
    parser.add_argument("--serving-duration", type=float, default=6.0)
    parser.add_argument("--serving-network", default="conv",
                        choices=("conv", "nature", "mlp"))
    parser.add_argument("--serving-max-batch", type=int, default=32)
    parser.add_argument("--skip-serving-net", action="store_true",
                        help="skip the socket serving-tier scale-out "
                        "section (1-vs-2 replica subprocess fleets)")
    parser.add_argument("--serving-net-clients", type=int, default=4,
                        help="closed-loop clients PER replica for "
                        "serving_net")
    parser.add_argument("--serving-net-duration", type=float, default=6.0)
    parser.add_argument("--serving-net-network", default="mlp",
                        choices=("conv", "nature", "mlp"))
    parser.add_argument("--serving-net-env", default="random:84x84x1")
    parser.add_argument("--skip-ckpt-stall", action="store_true",
                        help="skip the checkpoint_stall section (2M-slot "
                        "native dedup ring: ~17.6 GB RAM + a one-off "
                        "multi-GB full-snapshot disk write)")
    parser.add_argument("--ckpt-capacity", type=int, default=2_000_000,
                        help="slots for the checkpoint_stall dedup layout")
    parser.add_argument("--ckpt-interval-rows", type=int, default=65_536,
                        help="transitions ingested between incremental "
                        "saves (the checkpoint interval the delta covers)")
    parser.add_argument(
        "--ckpt-stall-only", action="store_true",
        help="run ONLY the checkpoint_stall section and print its JSON "
        "(artifact generation: demos/ckpt_stall.json)",
    )
    parser.add_argument("--skip-pipeline-overlap", action="store_true",
                        help="skip the overlapped-dispatch pipeline sweep "
                        "(CPU-pinned subprocess; depth 1/2/4)")
    parser.add_argument("--pipeline-overlap-steps", type=int, default=6400)
    parser.add_argument("--pipeline-overlap-sync-every", type=int,
                        default=1024)
    parser.add_argument("--skip-xp-transport", action="store_true",
                        help="skip the shm-ring vs mp.Queue transport bench")
    parser.add_argument("--skip-xp-net", action="store_true",
                        help="skip the shm-ring vs TCP-loopback transport "
                        "bench (xp_net)")
    parser.add_argument("--xp-workers", default="4,16,64",
                        help="comma-separated producer counts for "
                        "xp_transport")
    parser.add_argument("--xp-seconds", type=float, default=3.0)
    parser.add_argument("--skip-replay-svc", action="store_true",
                        help="skip the replay-as-a-service RPC vs "
                        "in-process section")
    parser.add_argument("--replay-svc-iters", type=int, default=300)
    parser.add_argument("--replay-svc-capacity", type=int, default=16_384)
    parser.add_argument("--replay-svc-rows", type=int, default=8_192)
    parser.add_argument("--skip-central-inference", action="store_true",
                        help="skip the central_inference section "
                        "(paramless vs param-holding workers at "
                        "4/16/64 — the longest host-only section: the "
                        "64-wide legs ramp a real process fleet)")
    parser.add_argument("--central-widths", default="4,16,64")
    parser.add_argument("--central-measure-s", type=float, default=20.0)
    parser.add_argument("--central-skip-kill", action="store_true",
                        help="skip the central_inference replica-kill "
                        "leg (the subprocess smoke; CI-tiny bench runs "
                        "keep the width points only)")
    parser.add_argument("--skip-replay-tiered", action="store_true",
                        help="skip the replay_tiered section (disk-spill "
                        "cold frame store vs in-core)")
    parser.add_argument("--replay-tiered-capacity", type=int,
                        default=200_000)
    parser.add_argument("--replay-tiered-iters", type=int, default=1000)
    parser.add_argument(
        "--replay-tiered-only", action="store_true",
        help="run ONLY the replay_tiered section and print its JSON "
        "(the demos/replay_tiered.json artifact)",
    )
    parser.add_argument(
        "--xp-transport-smoke", action="store_true",
        help="CI gate: run ONLY a tiny xp_transport point + barrage "
        "(host-only, no backend probe, seconds not minutes) and exit — "
        "tools/verify_t1.sh uses this so an import-time regression in the "
        "transport can't reach the driver unseen",
    )
    args = parser.parse_args()

    if args.replay_tiered_only:
        print(json.dumps({"replay_tiered": _replay_tiered_bench(
            capacity=args.replay_tiered_capacity,
            iters=args.replay_tiered_iters,
        )}))
        return

    if args.ckpt_stall_only:
        print(json.dumps({"checkpoint_stall": _checkpoint_stall_bench(
            capacity=args.ckpt_capacity,
            interval_rows=args.ckpt_interval_rows,
        )}))
        return

    if args.xp_transport_smoke:
        out = _xp_transport_bench(workers=(2,), seconds=0.5, rows=16,
                                  obs_shape=(16, 16, 1), barrage_rounds=1)
        bar = out["sigkill_barrage"]
        assert bar["lost_committed_chunks"] == 0, bar
        assert bar["seq_errors"] == 0, bar
        print(json.dumps({"xp_transport_smoke": out}))
        return

    extra: dict = {}

    def section(key, fn, *a, **kw):
        """Fault isolation: a failing/slow optional section records its
        error instead of losing the whole (single-line) bench output."""
        try:
            extra[key] = fn(*a, **kw)
        except Exception as e:  # noqa: BLE001 — recorded, not fatal
            extra[key] = {"error": f"{type(e).__name__}: {e}"}

    # Outage gate (VERDICT round-5 item 1): decide whether the backend is
    # reachable in a subprocess with a hard timeout BEFORE any in-process
    # jax backend init can hang the bench.
    probe = _probe_backend(args.probe_timeout)
    extra["backend_probe"] = probe
    outage = not probe["ok"]
    # On-chip sections (fused headline, pipelines) need an accelerator: a
    # probe that "succeeds" on a CPU-only backend (JAX_PLATFORMS=cpu, or a
    # plugin falling back) must NOT send the conv-net fused scan to XLA-CPU
    # — one 128-step fused call exceeds 9 minutes on a 1-core VM, so the
    # driver's bench would burn hours producing meaningless numbers.  The
    # host-only sections carry the line instead (same shape as an outage).
    on_chip = not outage and probe.get("device_kind") != "cpu"
    if not outage and not on_chip:
        extra["on_chip_skipped"] = (
            "backend is cpu-only (device_kind=cpu): fused/pipeline "
            "sections are accelerator measurements and are skipped — "
            "host-only sections committed instead"
        )

    if on_chip:
        import jax  # noqa: F401 — backend verified reachable
        import jax.numpy as jnp

        # The on-chip headline, inside fault isolation like every other
        # section: a mid-run backend failure records an error field instead
        # of eating the bench line.
        section("fused", _fused_headline_bench, args)
        # Dedup twin of the headline: same workload over the frame-dedup
        # HBM ring (each frame once) — config3-scale layout per-step cost.
        section("dedup_fused", _dedup_fused_bench, args, jnp, jax)
        if not args.skip_sampler_validation:
            section("samplers_2m", _validate_samplers,
                    np.random.default_rng(12))
    if not args.skip_sampler_validation:
        section("host_replay_2m", _host_replay_bench,
                capacity=args.host_replay_capacity)
    if not args.skip_host_dedup:
        # Paper-scale host path on the native C++ dedup core.  The
        # n_stripes=1 number is the host ceiling on this 1-core VM;
        # striped4 shows the striped LAW's overhead only (the wrapper
        # serializes calls — striping is not realized parallelism here).
        section("host_dedup_2m", _host_dedup_bench,
                capacity=args.host_replay_capacity)
        section("host_dedup_2m_striped4", _host_dedup_bench,
                capacity=args.host_replay_capacity, n_stripes=4, iters=1000)
        if "error" not in extra["host_dedup_2m_striped4"]:
            extra["host_dedup_2m_striped4"]["note"] = (
                "striped sampling-law overhead probe; NOT parallel on this "
                "1-core host (wrapper serializes calls)"
            )
    if not args.skip_serving:
        # Host-only like host_replay/host_dedup: the loadgen child pins
        # itself to CPU, so the serving number survives tunnel outages.
        section("serving_qps", _serving_bench,
                clients=args.serving_clients,
                duration=args.serving_duration,
                network=args.serving_network,
                max_batch=args.serving_max_batch)
    if not args.skip_serving_net:
        # Host-only like serving_qps: the SOCKET serving tier — 1 vs 2
        # routed replica subprocesses at matched per-replica load, delta
        # param fan-out cost per push (ISSUE 9; demos/serving_net.json is
        # the committed artifact with fault injection on top).
        section("serving_net", _serving_net_bench,
                clients_per_replica=args.serving_net_clients,
                duration=args.serving_net_duration,
                network=args.serving_net_network,
                env=args.serving_net_env)
    if not args.skip_pipeline_overlap:
        # Host-only (CPU-pinned subprocess): the overlapped dispatch
        # pipeline's sync-count / overlap accounting at depth 1/2/4 —
        # the sync amortization the tunnel's ~140 ms post-sync charge
        # makes worth measuring even when the chip is unreachable.
        section("pipeline_overlap", _pipeline_overlap_bench,
                steps=args.pipeline_overlap_steps,
                sync_every=args.pipeline_overlap_sync_every)
    if not args.skip_xp_transport:
        # Host-only (no jax in any producer/consumer): the actor→learner
        # transport in isolation, shm ring vs mp.Queue, + SIGKILL barrage.
        section("xp_transport", _xp_transport_bench,
                workers=tuple(int(w) for w in args.xp_workers.split(",")),
                seconds=args.xp_seconds)
    if not args.skip_xp_net:
        # Host-only (no jax in any producer/consumer): shm ring vs the
        # TCP backend over loopback — the cost of leaving /dev/shm
        # (ISSUE 8; demos/xp_net.json is the committed point set).
        section("xp_net", _xp_net_bench,
                workers=tuple(int(w) for w in args.xp_workers.split(",")),
                seconds=args.xp_seconds)
    if not args.skip_replay_tiered:
        # Host-only (no jax): the disk-spill cold frame store vs in-core —
        # sample/update with hot capped at 25% of frames (ROADMAP item 6;
        # demos/replay_tiered.json is the committed paper-scale point).
        section("replay_tiered", _replay_tiered_bench,
                capacity=args.replay_tiered_capacity,
                iters=args.replay_tiered_iters)
    if not args.skip_replay_svc:
        # Host-only (CPU-pinned subprocess; no jax anywhere in it): the
        # replay-as-a-service RPC plane vs in-process sampling — what
        # moving the replay out of the learner's address space costs per
        # batch (ROADMAP item 1; demos/replay_svc.json is the committed
        # point set).
        section("replay_svc", _replay_svc_bench,
                iters=args.replay_svc_iters,
                capacity=args.replay_svc_capacity,
                rows=args.replay_svc_rows)
    if not args.skip_central_inference:
        # Host-only (CPU-pinned subprocess): SEED-style paramless
        # workers vs param-holding ones at fleet width — env-steps/s
        # through the serving tier's micro-batcher, rtt percentiles,
        # and the replica-kill leg (ROADMAP item 2;
        # demos/central_inference.json is the committed point set).
        section("central_inference", _central_inference_bench,
                widths=args.central_widths,
                measure_s=args.central_measure_s,
                skip_kill_leg=args.central_skip_kill)
    if not args.skip_ckpt_stall:
        # Host-only: learner-visible checkpoint stall, full-sync vs the
        # incremental async subsystem, at the 2M-slot dedup layout.
        section("checkpoint_stall", _checkpoint_stall_bench,
                capacity=args.ckpt_capacity,
                interval_rows=args.ckpt_interval_rows)
    if on_chip and not args.skip_pipeline:
        section("actor_solo", _actor_solo_bench)
        extra["pipeline"] = _median_pipeline(
            args.pipeline_trials, learner_steps=args.pipeline_steps
        )
        # Second north-star metric: actor FPS.  The solo number is the
        # capability ceiling; the contended pipeline numbers show what one
        # tunneled chip sustains with the learner sharing the device FIFO
        # (PROFILE.md "pipeline contention" section).
        extra["actor_fps"] = extra["actor_solo"].get("actor_fps")
        extra["pipeline"]["contention_note"] = (
            "every host sync charges ~140 ms to the next dispatch on this "
            "tunneled platform, so concurrent actor+learner dispatch "
            "cannot interleave at us granularity; see PROFILE.md"
        )
        # The designed mitigation, chip-benchmarked (round-3 verdict item
        # 2): CPU-only worker-process actors leave the device to the
        # learner alone.  Learner steps/s should recover toward the solo
        # fused figure; actor FPS is host-core-bound (ONE core on this
        # driver VM — real deployments put workers on their own cores).
        # Two load points tell the story on this ONE-core driver VM: under
        # full worker load the learner's host dispatch thread is CPU-bound
        # against worker inference (a host-provisioning limit); with a
        # light fleet it recovers most of the solo rate — the device is the
        # learner's alone in both (that was the contention being fixed).
        extra["pipeline_process"] = _median_pipeline(
            args.pipeline_trials,
            learner_steps=32_768,
            steps_per_call=2048,
            actor_mode="process",
            num_workers=4,
            num_actors=256,
            min_replay=10_000,
        )
        extra["pipeline_process_light"] = _pipeline_bench(
            63_488,
            steps_per_call=2048,
            publish_every=16_384,
            actor_mode="process",
            num_workers=1,
            num_actors=8,
            min_replay=2_000,
            worker_nice=19,
        )
        # End-to-end DEDUP pipeline (thread mode, dedup HBM ring fed by
        # dedup-emitting actors) — the config3 storage layout live on the
        # chip; one trial (time-bounded), compare `pipeline`'s median.
        section("pipeline_dedup", _pipeline_bench,
                args.pipeline_steps, dedup=True)
        # process_vs_thread, settled (ROADMAP open item): a MATCHED pair —
        # same 256 actors, same 32768 learner steps, same steps_per_call,
        # median of the same number of trials — instead of comparing the
        # historical sections' different shapes.  Thread-mode actors run
        # jitted policy forwards on the learner's device; process-mode
        # workers are truly CPU-only (jax_platforms=cpu pinned via
        # jax.config in-child BEFORE any backend init — the round-5 fix;
        # chunks ride the shm-ring transport).
        extra["pipeline_thread_matched"] = _median_pipeline(
            args.pipeline_trials,
            learner_steps=32_768,
            steps_per_call=2048,
            num_actors=256,
            min_replay=10_000,
        )
        p_thread = extra["pipeline_thread_matched"][
            "median_window_steps_per_sec"]
        p_proc = extra["pipeline_process"]["median_window_steps_per_sec"]
        extra["process_vs_thread"] = {
            "thread_median": p_thread,
            "process_median": p_proc,
            "winner": "process" if p_proc > p_thread else "thread",
            "process_beats_thread": bool(p_proc > p_thread),
            "matched_config": {
                "num_actors": 256, "learner_steps": 32_768,
                "steps_per_call": 2048, "min_replay": 10_000,
                "trials": args.pipeline_trials,
            },
            "note": (
                "medians of the steady-state window rate over "
                f"{args.pipeline_trials} matched trials per mode "
                "(pipeline_thread_matched vs pipeline_process); workers "
                "are truly CPU-only in process mode"
            ),
        }
        extra["pipeline_process"]["note"] = (
            "4 CPU-inference workers × 64 actors each on a 1-core host: "
            "learner host thread contends with worker inference for the "
            "core (the device itself is uncontended — that is what process "
            "mode fixes); see pipeline_process_light for the same runtime "
            "under light worker load"
        )

    rate = extra.get("fused", {}).get("learner_steps_per_sec")
    print(
        json.dumps(
            {
                "metric": "learner_steps_per_sec",
                "value": rate,
                "unit": "steps/s",
                "vs_baseline": (
                    round(rate / NORTH_STAR_PER_CHIP, 3)
                    if rate is not None else None
                ),
                "platform_outage": outage,
                **extra,
            }
        )
    )


if __name__ == "__main__":
    main()
