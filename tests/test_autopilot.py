"""Elastic autopilot (ISSUE 15): controller decision logic on synthetic
SLO streams (no subprocesses), guardrail units, the autopilot schema
pin, and the pool's elastic grow/retire arithmetic — plus one real
process-pool grow/retire e2e (the only test here that spawns anything).
"""

from __future__ import annotations

import os
import time

import pytest

from ape_x_dqn_tpu.autopilot import AutopilotController, Guardrails
from ape_x_dqn_tpu.config import ApexConfig, AutopilotConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_cfg(**kw) -> AutopilotConfig:
    base = dict(
        enabled=True, dry_run=False, poll_s=0.1,
        actor_min_workers=1, serving_min_replicas=1,
        serving_max_replicas=4, cooldown_up_s=5.0, cooldown_down_s=5.0,
        hold_opposite_s=8.0, serving_idle_qps_per_replica=0.0,
        idle_window_s=10.0, drain_tune_max_factor=4.0,
    )
    base.update(kw)
    return AutopilotConfig(**base)


class FakeServing:
    def __init__(self, size=1, busy=False, exhausted=False):
        self._size = size
        self._busy = busy
        self._exhausted = exhausted
        self.calls = []

    def size(self):
        return self._size

    def busy(self):
        return self._busy

    def scale_up(self):
        if self._exhausted:
            return None
        self.calls.append("up")
        self._size += 1
        return {"rid": self._size}

    def scale_down(self):
        if self._exhausted:
            return None
        self.calls.append("down")
        self._size -= 1
        return {"rid": self._size + 1}


class FakeActor(FakeServing):
    def __init__(self, size=1, capacity=4, drain_factor_max=4.0, **kw):
        super().__init__(size=size, **kw)
        self._capacity = capacity
        self._drain = 1.0
        self.pipeline_tunes = 0

    def capacity(self):
        return self._capacity

    def drain_factor(self):
        return self._drain

    def tune_drain(self):
        self.calls.append("tune_drain")
        self._drain *= 2
        return {"factor": self._drain}

    def tune_pipeline(self):
        # One-shot, like the real ActorPoolActuator: the degrade can
        # only happen once per run.
        if self.pipeline_tunes:
            return None
        self.calls.append("tune_pipeline")
        self.pipeline_tunes += 1
        return {"pipeline_depth": 1}


def breach(ctl, rule, **fields):
    ctl.on_slo_event("slo_breach", rule=rule, value=1.0, bound=0.5,
                     **fields)


def clear(ctl, rule):
    ctl.on_slo_event("slo_clear", rule=rule, value=0.1, bound=0.5)


# ---------------------------------------------------------------------------
# Guardrails.
# ---------------------------------------------------------------------------


class TestGuardrails:
    def g(self, **kw):
        base = dict(min_size=1, max_size=3, cooldown_up_s=10.0,
                    cooldown_down_s=20.0, hold_opposite_s=30.0)
        base.update(kw)
        return Guardrails(**base)

    def test_bounds_clamp(self):
        g = self.g()
        assert g.check("up", 3, now=0.0) == "at_max"
        assert g.check("down", 1, now=0.0) == "at_min"
        assert g.check("up", 2, now=0.0) is None
        # Tuning actions bypass the size bounds, not the cooldowns.
        assert g.check("up", 3, now=0.0, bounded=False) is None

    def test_per_direction_cooldown(self):
        g = self.g()
        g.record("up", 0.0)
        assert g.check("up", 2, now=5.0) == "cooldown"
        assert g.check("up", 2, now=10.1) is None
        assert round(g.remaining("up", 5.0), 1) == 5.0

    def test_hold_opposite_outlasts_own_cooldown(self):
        g = self.g()
        g.record("up", 0.0)
        # Down's own cooldown never armed — the opposite-direction hold
        # is what blocks the reversal.
        assert g.check("down", 2, now=25.0) == "hold"
        assert g.check("down", 2, now=30.1) is None

    def test_busy_blocks_everything(self):
        g = self.g()
        assert g.check("up", 2, now=0.0, busy=True) == "busy"

    def test_unknown_direction_raises(self):
        with pytest.raises(ValueError):
            self.g().check("sideways", 2, now=0.0)


# ---------------------------------------------------------------------------
# Controller decisions (synthetic event streams, injected clocks).
# ---------------------------------------------------------------------------


class TestControllerDecisions:
    def ctl(self, cfg=None, serving=None, actor=None, rollup=None,
            events=None):
        emitted = events if events is not None else []
        c = AutopilotController(
            cfg or make_cfg(),
            rollup_fn=(lambda: rollup) if rollup is not None else None,
            emit=lambda name, **f: emitted.append((name, f)),
        )
        if serving is not None:
            c.attach_serving(serving)
        if actor is not None:
            c.attach_actor(actor)
        return c

    def test_scale_up_on_breach_then_cooldown_suppression(self):
        srv = FakeServing(size=1)
        events = []
        c = self.ctl(serving=srv, events=events)
        breach(c, "serving_p99_ms")
        acted = c.step(now=0.0)
        assert [a["action"] for a in acted] == ["scale_up"]
        assert srv.calls == ["up"] and srv.size() == 2
        assert [n for n, _ in events] == ["autopilot_action"]
        assert events[0][1]["rule"] == "serving_p99_ms"
        assert events[0][1]["size_from"] == 1
        assert events[0][1]["size_to"] == 2
        # Still breaching inside the cooldown: suppressed, not actuated.
        assert c.step(now=2.0) == []
        assert srv.size() == 2
        assert c.suppressed.get("serving:up:cooldown") == 1
        # Cooldown elapsed, breach still standing: one more step.
        assert [a["action"] for a in c.step(now=6.0)] == ["scale_up"]
        assert srv.size() == 3

    def test_clear_stops_scaling(self):
        srv = FakeServing(size=1)
        c = self.ctl(serving=srv)
        breach(c, "serving_p99_ms")
        c.step(now=0.0)
        clear(c, "serving_p99_ms")
        assert c.step(now=10.0) == []
        assert srv.size() == 2

    def test_bounds_clamp_at_max(self):
        srv = FakeServing(size=4)
        c = self.ctl(serving=srv)
        breach(c, "serving_qps")
        assert c.step(now=0.0) == []
        assert c.suppressed.get("serving:up:at_max") == 1
        assert srv.calls == []

    def test_busy_holds_scale_up(self):
        srv = FakeServing(size=1, busy=True)
        c = self.ctl(serving=srv)
        breach(c, "serving_p99_ms")
        assert c.step(now=0.0) == []
        assert c.suppressed.get("serving:up:busy") == 1

    def test_dry_run_is_inert(self):
        srv = FakeServing(size=1)
        events = []
        c = self.ctl(cfg=make_cfg(dry_run=True), serving=srv,
                     events=events)
        breach(c, "serving_p99_ms")
        acted = c.step(now=0.0)
        assert [a["action"] for a in acted] == ["scale_up"]
        assert acted[0]["dry_run"] is True
        assert srv.calls == [] and srv.size() == 1   # nothing actuated
        assert c.decisions == 1 and c.actions == 0
        # Cooldowns still arm: the dry run previews the REAL cadence.
        assert c.step(now=2.0) == []
        assert c.suppressed.get("serving:up:cooldown") == 1

    def test_both_fleet_independence(self):
        srv = FakeServing(size=1)
        act = FakeActor(size=1, capacity=4)
        c = self.ctl(serving=srv, actor=act)
        breach(c, "age_p95_ms")            # actor rule only
        acted = c.step(now=0.0)
        assert [a["fleet"] for a in acted] == ["actor"]
        assert act.size() == 2 and srv.size() == 1
        # A serving breach right after: its fleet's guardrails are its
        # own — the actor action did not consume serving's cooldown.
        breach(c, "serving_p99_ms")
        acted = c.step(now=0.1)
        assert [a["fleet"] for a in acted] == ["serving"]
        assert srv.size() == 2

    def test_actor_ceiling_degrades_pipeline_once(self):
        act = FakeActor(size=4, capacity=4)
        c = self.ctl(actor=act)
        breach(c, "age_p95_ms")
        acted = c.step(now=0.0)
        assert [a["action"] for a in acted] == ["tune_pipeline"]
        assert act.pipeline_tunes == 1
        # The hook self-disarms after the one degrade: further breached
        # steps at the ceiling are a plain at_max suppression.
        acted = c.step(now=10.0)
        assert acted == [] or all(
            a["action"] != "tune_pipeline" for a in acted)
        assert act.pipeline_tunes == 1

    def test_ring_occupancy_ladder_tunes_drain_before_retiring(self):
        act = FakeActor(size=3, capacity=4)
        cfg = make_cfg(drain_tune_max_factor=4.0, cooldown_down_s=1.0,
                       hold_opposite_s=0.0)
        c = self.ctl(cfg=cfg, actor=act)
        breach(c, "ring_occupancy")
        assert [a["action"] for a in c.step(now=0.0)] == ["tune_drain"]
        assert [a["action"] for a in c.step(now=2.0)] == ["tune_drain"]
        assert act.drain_factor() == 4.0
        # Ladder exhausted: only now does a worker retire.
        assert [a["action"] for a in c.step(now=4.0)] == ["scale_down"]
        assert act.size() == 2

    def test_flap_damping_hold_opposite(self):
        cfg = make_cfg(cooldown_up_s=1.0, cooldown_down_s=1.0,
                       hold_opposite_s=20.0,
                       serving_idle_qps_per_replica=5.0,
                       idle_window_s=10.0)
        srv = FakeServing(size=2)
        rollup = {"serving": {"replicas": 2, "qps": 0.5}}
        c = self.ctl(cfg=cfg, serving=srv, rollup=rollup)
        breach(c, "serving_p99_ms")
        c.step(now=0.0)
        assert srv.size() == 3
        clear(c, "serving_p99_ms")
        # Idle rule breaches (burn window: >=3 low samples), but the
        # opposite-direction hold blocks the reversal until t=20.
        for t in (1.0, 2.0, 3.0, 4.0):
            c.step(now=t)
        assert srv.size() == 3
        assert any(k == "serving:down:hold" for k in c.suppressed)
        acted = c.step(now=21.0)
        assert [a["action"] for a in acted] == ["scale_down"]
        assert acted[0]["rule"] == "serving_idle"
        assert srv.size() == 2

    def test_idle_scale_down_needs_green_up_rules(self):
        cfg = make_cfg(serving_idle_qps_per_replica=5.0,
                       hold_opposite_s=0.0, idle_window_s=10.0)
        srv = FakeServing(size=2)
        rollup = {"serving": {"replicas": 2, "qps": 0.5}}
        c = self.ctl(cfg=cfg, serving=srv, rollup=rollup)
        breach(c, "serving_p99_ms")      # an up-rule stands
        for t in (0.0, 1.0, 2.0, 3.0):
            c.step(now=t)
        # Idle is breaching by now, but the standing up-breach wins
        # (scale-up attempts, then at_max/cooldown — never a down).
        assert "down" not in srv.calls

    def test_exhausted_actuator_is_suppression_not_crash(self):
        srv = FakeServing(size=2, exhausted=True)
        c = self.ctl(serving=srv)
        breach(c, "serving_p99_ms")
        assert c.step(now=0.0) == []
        assert c.suppressed.get("serving:up:exhausted") == 1
        # No cooldown armed by a no-op: the next step retries at once.
        assert c.step(now=0.1) == []
        assert c.suppressed.get("serving:up:exhausted") == 2

    def test_unknown_rules_and_foreign_events_ignored(self):
        srv = FakeServing(size=1)
        c = self.ctl(serving=srv)
        c.on_slo_event("slo_breach", rule="endpoints_alive")
        c.on_slo_event("slo_breach", rule="no_such_rule")
        c.on_slo_event("worker_death", worker=3)
        assert c.step(now=0.0) == []
        assert srv.calls == []

    def test_state_matches_doc_schema(self):
        from ape_x_dqn_tpu.analysis.metrics_doc import doc_section_keys

        doc = doc_section_keys(
            "## Autopilot schema",
            os.path.join(REPO, "docs", "METRICS.md"))
        assert doc, "Autopilot schema doc section missing"
        c = self.ctl(serving=FakeServing(), actor=FakeActor())
        state = c.state(now=0.0)
        assert set(doc) == set(state), set(doc) ^ set(state)
        for fleet in state["fleets"].values():
            assert {"size", "min", "max", "busy", "breaching",
                    "last_action", "last_rule", "cooldown_up_s",
                    "cooldown_down_s"} == set(fleet)


# ---------------------------------------------------------------------------
# Pool elastic arithmetic (no processes spawned).
# ---------------------------------------------------------------------------


def _pool_cfg(num_workers=1, max_workers=3, num_actors=6) -> ApexConfig:
    cfg = ApexConfig()
    cfg.network = "mlp"
    cfg.env.name = "chain:6"
    cfg.actor.mode = "process"
    cfg.actor.num_workers = num_workers
    cfg.actor.max_workers = max_workers
    cfg.actor.num_actors = num_actors
    cfg.actor.T = 100_000
    cfg.actor.flush_every = 8
    cfg.learner.min_replay_mem_size = 64
    cfg.replay.capacity = 4096
    return cfg.validate()


class TestPoolElasticArithmetic:
    def test_partition_is_carved_over_capacity_not_live_width(self):
        """worker_slice over local_capacity never moves as the live
        width changes — the growth-never-reshuffles contract."""
        from ape_x_dqn_tpu.runtime.process_actors import worker_slice

        cap, actors = 3, 6
        slices = [worker_slice(w, actors, cap) for w in range(cap)]
        assert slices == [(0, 2), (2, 4), (4, 6)]
        # Growing from 1 to 3 live workers changes NOTHING about any
        # wid's slice (they are a pure function of wid and capacity),
        # and the slices tile the global set exactly.
        assert sorted(x for lo, hi in slices for x in range(lo, hi)) \
            == list(range(actors))

    def test_pool_capacity_candidates_and_budgets(self):
        from ape_x_dqn_tpu.config import transport_budget
        from ape_x_dqn_tpu.runtime.process_actors import ProcessActorPool

        cfg = _pool_cfg(num_workers=1, max_workers=3)
        pool = ProcessActorPool(cfg, num_workers=1)
        try:
            assert pool.local_capacity == 3
            assert pool.total_workers == 3
            assert pool.live_workers() == []          # nothing spawned
            assert pool.grow_candidates() == [0, 1, 2]
            assert not pool.finished                  # pre-start guard
            # transport_budget at the LIVE width must agree with the
            # pool's live accounting as width changes (the satellite's
            # mid-run consistency pin — here at width 0 with no rings).
            acc = pool.shm_accounting()
            assert acc["ring_bytes_total"] == 0
            tb = transport_budget(cfg, num_workers=0)
            assert tb["ring_bytes_total"] == 0
            tb3 = transport_budget(cfg, num_workers=3)
            assert tb3["ring_bytes_total"] \
                == 3 * cfg.actor.xp_ring_bytes
            # Drain-budget tuning clamps at the floor and reports live.
            base = pool.drain_budget_bytes
            assert pool.set_drain_budget(base * 2) == base * 2
            assert pool.set_drain_budget(1) == 64 << 10
        finally:
            pool.stop()

    def test_max_workers_validation(self):
        cfg = _pool_cfg()
        cfg.actor.max_workers = 1        # < num_workers... num_workers=1 ok
        cfg.validate()
        cfg.actor.num_workers = 2
        with pytest.raises(ValueError, match="max_workers"):
            cfg.validate()
        cfg = _pool_cfg()
        cfg.actor.mode = "thread"
        with pytest.raises(ValueError, match="mode=process"):
            cfg.validate()
        cfg = _pool_cfg()
        cfg.actor.num_actors = 2         # capacity 3 > 2 actors
        with pytest.raises(ValueError, match="reserved worker capacity"):
            cfg.validate()


# ---------------------------------------------------------------------------
# Real process grow/retire e2e (the one spawning test).
# ---------------------------------------------------------------------------


class TestPoolGrowRetireE2E:
    def test_grow_then_clean_retire(self):
        from ape_x_dqn_tpu.runtime.process_actors import ProcessActorPool

        cfg = _pool_cfg(num_workers=1, max_workers=2, num_actors=4)
        pool = ProcessActorPool(cfg, num_workers=1, quantum=8)
        from ape_x_dqn_tpu.runtime.process_actors import (
            network_and_template,
        )
        import jax

        _, _, template = network_and_template(cfg)
        try:
            pool.start()
            pool.publish(template)
            deadline = time.monotonic() + 120.0

            def drain_until(cond, what):
                while time.monotonic() < deadline:
                    pool.supervise()
                    pool.poll(max_items=64, timeout=0.05)
                    if cond():
                        return
                raise TimeoutError(what)

            drain_until(lambda: 0 in pool.last_versions,
                        "wid 0 first chunk")
            # Post-start grow: the reserved wid comes up on the same
            # spawn path and delivers its own slice's chunks.
            assert pool.grow(1) == [1]
            assert pool.live_workers() == [0, 1]
            assert pool.shm_accounting()["ring_bytes_total"] \
                == 2 * cfg.actor.xp_ring_bytes
            drain_until(lambda: 1 in pool.last_versions,
                        "grown wid 1 first chunk")
            steps_before = pool._steps_by_worker.get(1, 0)
            assert steps_before > 0
            # Clean retire of the highest wid: drains, exits "done",
            # never a respawn, never an error, ring reclaimed.
            assert pool.retire() == 1
            drain_until(lambda: 1 in pool.finished_workers
                        and 1 not in pool._rings,
                        "retired wid 1 clean done + ring reclaim")
            assert pool.live_workers() == [0]
            assert not pool.worker_errors
            assert pool.restarts == 0
            assert pool.retired == {1}
            assert pool.transport.summary()["torn_records"] == 0
            assert pool.shm_accounting()["ring_bytes_total"] \
                == 1 * cfg.actor.xp_ring_bytes
            # The freed slot is a grow candidate again (remaining-budget
            # arithmetic: it consumed steps, so its budget shrank).
            assert pool.grow_candidates() == [1]
            assert pool._steps_by_worker[1] >= steps_before
        finally:
            pool.stop()
