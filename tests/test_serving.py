"""Policy-serving subsystem (serving/): bucket padding, deadline flush,
admission control, hot-reload atomicity, checkpoint source, metrics.

The ISSUE-pinned behaviors: padded rows never influence real rows' argmax;
a lone request flushes at the max-wait deadline (not never); a full queue
sheds with the typed error (not unbounded growth); a param swap lands
between batches — every reply's version matches the params that actually
produced its Q-values.
"""

import threading
import time

import numpy as np
import pytest

from ape_x_dqn_tpu.models.dueling import build_network
from ape_x_dqn_tpu.runtime.param_store import ParamStore
from ape_x_dqn_tpu.serving import (
    MicroBatcher,
    PolicyServer,
    ServerClosed,
    ServerOverloaded,
    bucket_for,
    bucket_sizes,
)
from ape_x_dqn_tpu.utils.metrics import LatencyHistogram

OBS = (6,)
A = 3


def make_net_and_params(seed=0):
    import jax

    net = build_network("mlp", A, hidden_sizes=(16,))
    params = net.init(jax.random.PRNGKey(seed), np.zeros((1, *OBS), np.uint8))
    return net, params


def ref_q(net, params, obs):
    """Batch-1 reference forward — the oracle every served row must match."""
    return np.asarray(net.apply(params, obs[None])[2][0])


class TestBuckets:
    def test_bucket_ladder(self):
        assert bucket_sizes(1) == [1]
        assert bucket_sizes(8) == [1, 2, 4, 8]
        assert bucket_sizes(32) == [1, 2, 4, 8, 16, 32]
        # Non-power-of-two max always included as the top bucket.
        assert bucket_sizes(12) == [1, 2, 4, 8, 12]

    def test_bucket_for(self):
        buckets = bucket_sizes(8)
        assert bucket_for(1, buckets) == 1
        assert bucket_for(3, buckets) == 4
        assert bucket_for(8, buckets) == 8
        with pytest.raises(ValueError):
            bucket_for(9, buckets)


class TestPaddingCorrectness:
    def test_padded_rows_never_influence_real_rows(self):
        """5 concurrent requests ride one bucket-8 batch (3 padded rows);
        every reply's action and Q must equal the batch-1 oracle."""
        net, params = make_net_and_params()
        server = PolicyServer(
            net, params, max_batch=8, max_wait_ms=100.0, queue_capacity=16
        )
        server.warmup(OBS)
        server.start()
        try:
            rng = np.random.default_rng(3)
            obs = [rng.integers(0, 255, OBS, dtype=np.uint8) for _ in range(5)]
            futures = [server.submit(o) for o in obs]
            results = [f.result(timeout=10.0) for f in futures]
            # All five coalesced into one batch (the 100 ms deadline was
            # plenty for five same-thread submits).
            assert server.stats()["batch_hist"].get("5") == 1
            for o, r in zip(obs, results):
                q = ref_q(net, params, o)
                np.testing.assert_allclose(r.q_values, q, atol=1e-4)
                assert r.action == int(np.argmax(q))
        finally:
            server.close()

    def test_every_bucket_shape_matches_oracle(self):
        """Each bucket size (1, 2, 4, 8) with its padding produces
        per-row-correct argmax — no shape's compiled program leaks padding
        into real rows."""
        net, params = make_net_and_params()
        server = PolicyServer(
            net, params, max_batch=8, max_wait_ms=50.0, queue_capacity=16
        )
        server.warmup(OBS)
        server.start()
        rng = np.random.default_rng(11)
        try:
            for n in (1, 2, 3, 5, 8):
                obs = [
                    rng.integers(0, 255, OBS, dtype=np.uint8)
                    for _ in range(n)
                ]
                results = [
                    f.result(timeout=10.0)
                    for f in [server.submit(o) for o in obs]
                ]
                for o, r in zip(obs, results):
                    assert r.action == int(np.argmax(ref_q(net, params, o)))
        finally:
            server.close()


class TestDeadlineFlush:
    def test_lone_request_flushes_at_deadline(self):
        """At QPS ~0 a single request must complete in ~max_wait, not wait
        for a full bucket that is never coming."""
        net, params = make_net_and_params()
        server = PolicyServer(
            net, params, max_batch=32, max_wait_ms=30.0, queue_capacity=16
        )
        server.warmup(OBS)
        server.start()
        try:
            t0 = time.monotonic()
            res = server.act(np.zeros(OBS, np.uint8), timeout=10.0)
            wall = time.monotonic() - t0
            assert res.action in range(A)
            # Generous bound for a contended CI host: deadline (30 ms) +
            # one batch-1 apply + scheduler noise, nowhere near "forever".
            assert wall < 2.0, f"lone request took {wall:.3f}s"
            assert server.stats()["batch_hist"].get("1") >= 1
        finally:
            server.close()


class TestAdmissionControl:
    def test_load_shed_at_queue_capacity(self):
        """Queue full -> typed ServerOverloaded, shed counted, and queued
        requests still complete once the worker unblocks."""
        release = threading.Event()
        entered = threading.Event()

        def blocking_run(obs):
            entered.set()
            release.wait(timeout=10.0)
            n = obs.shape[0]
            return np.zeros(n, np.int32), np.zeros((n, A), np.float32), 0

        b = MicroBatcher(
            blocking_run, max_batch=1, max_wait_s=0.0, queue_capacity=3
        )
        b.start()
        first = b.submit(np.zeros(OBS, np.uint8))
        assert entered.wait(timeout=5.0)        # worker holds request #0
        queued = [b.submit(np.zeros(OBS, np.uint8)) for _ in range(3)]
        with pytest.raises(ServerOverloaded):
            b.submit(np.zeros(OBS, np.uint8))   # 4th: queue full -> shed
        assert b.shed_count == 1
        release.set()
        for f in [first, *queued]:
            assert f.result(timeout=10.0).action == 0
        assert b.shed_count == 1               # shed didn't double-count
        b.close()

    def test_closed_server_rejects_typed(self):
        net, params = make_net_and_params()
        server = PolicyServer(net, params, max_batch=2, queue_capacity=4)
        server.start()
        server.close()
        with pytest.raises(ServerClosed):
            server.submit(np.zeros(OBS, np.uint8))


class TestHotReload:
    def test_version_swap_atomicity(self):
        """Every reply's reported version matches the params that actually
        computed its Q-values — a swap can land only between batches, and
        no request is dropped or errored across it."""
        import jax

        net, p0 = make_net_and_params(seed=0)
        _, p1 = make_net_and_params(seed=1)
        by_version = {0: jax.device_get(p0), 1: jax.device_get(p1)}
        store = ParamStore(p0)
        server = PolicyServer(
            net, param_source=store, max_batch=4, max_wait_ms=2.0,
            queue_capacity=64, reload_poll_s=0.02,
        )
        server.warmup(OBS)
        server.start()
        results = []          # (obs, ServedAction)
        errors = []
        stop = threading.Event()

        def client(seed):
            rng = np.random.default_rng(seed)
            while not stop.is_set():
                obs = rng.integers(0, 255, OBS, dtype=np.uint8)
                try:
                    results.append((obs, server.act(obs, timeout=10.0)))
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        threads = [
            threading.Thread(target=client, args=(i,), daemon=True)
            for i in range(4)
        ]
        try:
            for t in threads:
                t.start()
            time.sleep(0.3)
            store.publish(p1)                   # the hot swap
            deadline = time.monotonic() + 5.0
            while server.param_version < 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert server.param_version == 1, "reload never adopted"
            time.sleep(0.3)
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10.0)
            server.close()
        assert not errors, f"requests dropped/errored across swap: {errors[:3]}"
        seen = {r.param_version for _, r in results}
        assert seen == {0, 1}, f"expected traffic on both versions, saw {seen}"
        # Batched oracle per version (one forward per version, not one
        # trace per reply): every reply's Q must match the params of the
        # version it CLAIMS served it — a torn/mixed swap cannot pass.
        for version, params in by_version.items():
            group = [(o, r) for o, r in results if r.param_version == version]
            obs_batch = np.stack([o for o, _ in group])
            q_ref = np.asarray(net.apply(params, obs_batch)[2])
            q_got = np.stack([r.q_values for _, r in group])
            np.testing.assert_allclose(
                q_got, q_ref, atol=1e-4,
                err_msg="replies' q_values disagree with their reported "
                "version's params — torn/mixed swap",
            )
            actions = np.array([r.action for _, r in group])
            np.testing.assert_array_equal(actions, np.argmax(q_ref, axis=-1))
        assert server.reload_count == 1


class TestCheckpointSource:
    def test_checkpoint_dir_versions(self, tmp_path):
        import jax

        from ape_x_dqn_tpu.learner.train_step import (
            init_train_state,
            make_optimizer,
        )
        from ape_x_dqn_tpu.serving import CheckpointParamSource
        from ape_x_dqn_tpu.utils.checkpoint import save_checkpoint

        net, _ = make_net_and_params()
        opt = make_optimizer("adam")
        state = init_train_state(
            net, opt, jax.random.PRNGKey(0), np.zeros((1, *OBS), np.uint8)
        )
        source = CheckpointParamSource(str(tmp_path), state)
        assert source.version == -1
        assert source.get(-1) is None           # empty dir: nothing to serve
        save_checkpoint(str(tmp_path), state)   # step 0
        got = source.get(-1)
        assert got is not None
        params, version = got
        assert version == 0
        np.testing.assert_allclose(
            jax.tree_util.tree_leaves(params)[0],
            jax.tree_util.tree_leaves(jax.device_get(state.params))[0],
        )
        assert source.get(0) is None            # already current
        newer = state.replace(step=state.step + 7)
        save_checkpoint(str(tmp_path), newer)   # step 7 commits
        params, version = source.get(0)
        assert version == 7
        assert source.version == 7

    def test_in_progress_incremental_save_never_observed(self, tmp_path):
        """An in-flight incremental save (chunk files on disk, manifest
        not yet rewritten; a step dir without its orbax state commit) must
        never move latest_step or the served version — the manifest-last /
        state-dir-last commit ordering is what CheckpointParamSource's
        atomicity rests on (utils/checkpoint_inc)."""
        import os

        import jax

        from ape_x_dqn_tpu.learner.train_step import (
            init_train_state,
            make_optimizer,
        )
        from ape_x_dqn_tpu.serving import CheckpointParamSource
        from ape_x_dqn_tpu.utils import checkpoint_inc as ci
        from ape_x_dqn_tpu.utils.checkpoint import save_checkpoint

        net, _ = make_net_and_params()
        state = init_train_state(
            net, make_optimizer("adam"), jax.random.PRNGKey(0),
            np.zeros((1, *OBS), np.uint8),
        )
        save_checkpoint(str(tmp_path), state)   # step 0 commits
        source = CheckpointParamSource(str(tmp_path), state)
        assert source.version == 0
        # A writer mid-save: replay chunks (+ a torn manifest tmp) and a
        # step dir whose orbax state/ marker hasn't landed yet.
        inc = ci.inc_dir(str(tmp_path))
        os.makedirs(inc)
        ci.write_chunk(os.path.join(inc, "chunk_0_0.ckpt"),
                       {"x": np.arange(8)})
        with open(os.path.join(inc, "MANIFEST.json.tmp"), "w") as f:
            f.write('{"half')
        os.makedirs(str(tmp_path / "step_9"))
        assert source.version == 0              # nothing new observed
        assert source.get(0) is None
        params, version = source.get(-1)        # still serves the commit
        assert version == 0
        # The state commit is what flips the version — and only then.
        newer = state.replace(step=state.step + 9)
        save_checkpoint(str(tmp_path), newer)
        assert source.get(0)[1] == 9


class TestLatencyHistogram:
    def test_percentiles_within_bucket_error(self):
        h = LatencyHistogram()
        rng = np.random.default_rng(0)
        samples = rng.uniform(0.001, 0.1, size=5000)
        for s in samples:
            h.record(s)
        for p in (50, 95, 99):
            exact = float(np.percentile(samples, p))
            got = h.percentile(p)
            # One geometric bucket of relative error (20/decade ~ 12%).
            assert exact * 0.85 <= got <= exact * 1.15, (p, exact, got)
        s = h.summary()
        assert s["count"] == 5000
        assert s["p50_ms"] <= s["p95_ms"] <= s["p99_ms"] <= s["max_ms"]

    def test_empty_and_clamp(self):
        h = LatencyHistogram()
        assert h.summary() == {"count": 0}
        h.record(0.020)
        # A single sample: every percentile clamps to the observed max.
        assert h.percentile(50) == pytest.approx(0.020, rel=0.15)
        assert h.percentile(99) <= 0.020 + 1e-9


class TestServingConfig:
    def test_validation(self):
        from ape_x_dqn_tpu.config import ApexConfig

        cfg = ApexConfig()
        cfg.serving.max_batch = 64
        cfg.serving.queue_capacity = 32     # < max_batch: not admissible
        with pytest.raises(ValueError, match="queue_capacity"):
            cfg.validate()

    def test_native_json_and_overrides(self, tmp_path):
        import json

        from ape_x_dqn_tpu.config import load_config

        f = tmp_path / "cfg.json"
        f.write_text(json.dumps({
            "env": {"name": "chain:6"}, "network": "mlp",
            "serving": {"max_batch": 16, "max_wait_ms": 2.5},
        }))
        cfg = load_config(str(f), overrides=["serving.queue_capacity=99"])
        assert cfg.serving.max_batch == 16
        assert cfg.serving.max_wait_ms == 2.5
        assert cfg.serving.queue_capacity == 99


class TestLoadgen:
    def test_quick_closed_loop_run(self):
        import os
        import sys

        sys.path.insert(
            0, os.path.join(os.path.dirname(__file__), "..", "tools")
        )
        try:
            from loadgen import run_loadgen
        finally:
            sys.path.pop(0)
        r = run_loadgen(
            clients=4, duration=0.6, network="mlp", obs_shape=OBS,
            max_batch=8, seq_seconds=0.3, reloads=1, low_qps_requests=3,
        )
        assert r["concurrent"]["errors"] == 0
        assert r["concurrent"]["shed"] == 0
        assert r["concurrent"]["requests"] > 0
        assert r["reloads"]["observed"] >= 1
        assert r["checks"]["hot_reload_zero_dropped"]
        assert set(r["checks"]) == {
            "speedup_ge_5x", "hot_reload_zero_dropped",
            "p99_bounded", "low_qps_bounded",
        }


class TestServeCLI:
    def test_checkpoint_serve_smoke(self, tmp_path, capsys):
        """serve CLI end to end: checkpoint dir -> PolicyServer -> built-in
        clients -> serve/ metrics JSONL on stdout."""
        import json

        import jax

        from ape_x_dqn_tpu.config import ApexConfig
        from ape_x_dqn_tpu.learner.train_step import (
            init_train_state,
            make_optimizer,
        )
        from ape_x_dqn_tpu.serve import main
        from ape_x_dqn_tpu.utils.checkpoint import save_checkpoint

        cfg = ApexConfig()
        cfg.env.name = "chain:6"
        cfg.network = "mlp"
        from ape_x_dqn_tpu.runtime.components import build_components

        comps = build_components(cfg)
        save_checkpoint(str(tmp_path), comps.state)
        rc = main([
            "--checkpoint", str(tmp_path),
            "--set", "env.name=chain:6", "--set", "network=mlp",
            "--clients", "2", "--duration", "1.0",
            "--metrics-every", "0.4",
        ])
        assert rc == 0
        out = capsys.readouterr().out
        records = [json.loads(l) for l in out.splitlines() if l.strip()]
        assert records, "no metrics emitted"
        final = records[-1]
        assert final.get("final")
        assert final["serve/served_total"] > 0
        assert final["serve/shed_total"] == 0
        assert any("serve/qps" in r for r in records)

    def test_empty_checkpoint_dir_is_an_error(self, tmp_path):
        from ape_x_dqn_tpu.serve import main

        rc = main([
            "--checkpoint", str(tmp_path / "none"),
            "--set", "env.name=chain:6", "--set", "network=mlp",
            "--duration", "0.2",
        ])
        assert rc == 2
