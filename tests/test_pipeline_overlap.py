"""Overlapped dispatch pipeline (ISSUE 5): equivalence, accounting, and
runtime wiring.

The load-bearing test is strict-vs-overlapped **bit-for-bit equivalence**:
pipeline_depth > 1 changes WHERE host work happens (stager thread, folded
ingest dispatch, deferred drains) but must not change a single bit of the
params, the replay ring, or the priorities — the overlap is free lunch,
not a semantics knob.
"""

from __future__ import annotations

import io
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ape_x_dqn_tpu.learner.train_step import (
    build_train_step,
    init_train_state,
    make_optimizer,
)
from ape_x_dqn_tpu.models.dueling import build_network
from ape_x_dqn_tpu.runtime.fused_learner import FusedDeviceLearner
from ape_x_dqn_tpu.runtime.infeed import DispatchPipeline
from ape_x_dqn_tpu.types import NStepTransition

OBS = (8, 8, 1)
A = 3


def _mk_learner(seed=0, K=4, B=8, C=256, block=32):
    net = build_network("mlp", A)
    opt = make_optimizer("adam", learning_rate=1e-3)
    state = init_train_state(
        net, opt, jax.random.PRNGKey(seed), jnp.zeros((1, *OBS), jnp.uint8)
    )
    return FusedDeviceLearner(
        net, opt, state, OBS, capacity=C, batch_size=B,
        steps_per_call=K, ingest_block=block, target_sync_freq=8,
        sample_ahead=True,
    )


def _chunk(rng, m):
    return (
        (np.abs(rng.normal(size=m)) + 0.1).astype(np.float32),
        NStepTransition(
            obs=rng.integers(0, 255, (m, *OBS), dtype=np.uint8),
            action=rng.integers(0, A, (m,), dtype=np.int32),
            reward=rng.normal(size=(m,)).astype(np.float32),
            discount=np.full((m,), 0.97, np.float32),
            next_obs=rng.integers(0, 255, (m, *OBS), dtype=np.uint8),
        ),
    )


def _assert_trees_equal(a, b, what):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y), what)


class TestStrictVsOverlappedEquivalence:
    def test_depth_gt_1_is_bit_for_bit_identical_to_strict(self):
        """Same seed, same chunk arrivals: strict (ingest inline, force
        every call) vs overlapped (stager split, folded last block,
        depth-3 window drained at the end) produce identical params,
        ring contents, priorities (mass), and staged leftovers."""
        chunks = [_chunk(np.random.default_rng(100 + r), 48)
                  for r in range(6)]

        strict = _mk_learner()
        for prio, trans in chunks:
            strict.add_chunk(prio, trans)
            strict.ingest_staged()
            m = strict.train(0.4)
            float(np.asarray(m.loss)[-1])  # force, strict-style

        over = _mk_learner()
        pipe = DispatchPipeline(3, probe_fn=lambda m: m.loss)
        for prio, trans in chunks:
            over.add_chunk(prio, trans)
            over.prepare_staged()  # the stager thread's half, inline here
            blocks = over.pop_prepared()
            fold = None
            if blocks and over.supports_ingest_fold \
                    and len(blocks[-1][0]) == 32:
                fold = blocks.pop()
            for blk in blocks:
                over.add_block(*blk)
            if fold is not None:
                pipe.dispatch(
                    lambda: over.train_with_ingest(0.4, fold[0], fold[1]),
                    over.steps_per_call,
                )
            else:
                pipe.dispatch(lambda: over.train(0.4), over.steps_per_call)
        pipe.sync()

        _assert_trees_equal(
            jax.device_get(strict.state), jax.device_get(over.state),
            "train state diverged",
        )
        sa, sb = strict.state_dict(), over.state_dict()
        assert set(sa) == set(sb)
        for k in sa:
            np.testing.assert_array_equal(
                np.asarray(sa[k]), np.asarray(sb[k]), f"ring field {k}"
            )
        assert strict.size == over.size
        assert strict.staged_rows == over.staged_rows

    def test_fold_is_identical_to_separate_add_then_train(self):
        """train_with_ingest (one dispatch) == add_block + train (two) —
        the fold saves a round trip, not a bit."""
        prio, trans = _chunk(np.random.default_rng(7), 32)
        warm = [_chunk(np.random.default_rng(8), 32)]

        def run(folded: bool):
            le = _mk_learner(seed=3)
            for p, t in warm:
                le.add_chunk(p, t)
                le.ingest_staged()
            if folded:
                m = le.train_with_ingest(0.4, prio, trans)
            else:
                le.add_block(prio, trans)
                m = le.train(0.4)
            np.asarray(m.loss)
            return jax.device_get(le.state), le.state_dict()

        (s1, r1), (s2, r2) = run(False), run(True)
        _assert_trees_equal(s1, s2, "fold changed the train state")
        for k in r1:
            np.testing.assert_array_equal(
                np.asarray(r1[k]), np.asarray(r2[k]), f"ring field {k}"
            )

    def test_fold_rejects_partial_block(self):
        le = _mk_learner()
        prio, trans = _chunk(np.random.default_rng(9), 16)
        with pytest.raises(ValueError, match="full ingest_block"):
            le.train_with_ingest(0.4, prio, trans)


class TestPreparedStaging:
    def test_prepared_rows_still_ride_staged_rows_and_snapshots(self):
        """A block that was carved but not yet dispatched must stay
        visible to checkpointing — prepare_staged moves rows between
        stages of the double buffer, it must not leak them."""
        le = _mk_learner()
        prio, trans = _chunk(np.random.default_rng(1), 40)
        le.add_chunk(prio, trans)
        assert le.staged_rows == 40
        le.prepare_staged()
        assert le.staged_rows == 40  # 32 prepared + 8 staged tail
        snap = le.state_dict()
        assert len(snap["staged_prio"]) == 40
        np.testing.assert_array_equal(snap["staged_prio"], prio)

    def test_prepare_then_dispatch_matches_inline_ingest(self):
        rng = np.random.default_rng(2)
        prio, trans = _chunk(rng, 80)
        a, b = _mk_learner(), _mk_learner()
        a.add_chunk(prio, trans)
        a.ingest_staged(drain=True)
        b.add_chunk(prio, trans)
        b.prepare_staged(drain=True)
        ingested = sum(b.add_block(*blk) for blk in b.pop_prepared())
        assert ingested == a.size == b.size
        for k, v in a.state_dict().items():
            np.testing.assert_array_equal(
                np.asarray(v), np.asarray(b.state_dict()[k]), k
            )


class _FakeProbe:
    """Duck-typed jax.Array stand-in with controllable readiness."""

    def __init__(self, ready=False):
        self.ready = ready
        self.copies = 0

    def is_ready(self):
        return self.ready

    def copy_to_host_async(self):
        self.copies += 1

    def __array__(self, dtype=None, copy=None):
        return np.zeros(1, np.float32)


class _GapSink:
    def __init__(self):
        self.values = []

    def observe(self, v):
        self.values.append(v)


class TestDispatchPipelineUnit:
    def test_strict_depth1_counts_a_sync_per_unready_call(self):
        pipe = DispatchPipeline(1, probe_fn=lambda p: p)
        for _ in range(5):
            pipe.dispatch(lambda: _FakeProbe(ready=False), steps=4)
        assert pipe.host_syncs == 5
        assert len(pipe) == 0

    def test_ready_calls_retire_free(self):
        pipe = DispatchPipeline(1, probe_fn=lambda p: p)
        for _ in range(5):
            pipe.dispatch(lambda: _FakeProbe(ready=True), steps=4)
        assert pipe.host_syncs == 0

    def test_depth_window_polls_instead_of_blocking(self):
        """At depth>1 a full window waits by polling; a probe that turns
        ready during the poll retires with NO counted sync."""
        pipe = DispatchPipeline(2, probe_fn=lambda p: p,
                                poll_s=1e-4, poll_deadline_s=5.0)
        probes = []

        def make():
            p = _FakeProbe(ready=False)
            probes.append(p)
            return p

        pipe.dispatch(make, steps=1)  # len 1 < depth: no wait

        import threading

        def release():
            time.sleep(0.05)
            probes[0].ready = True

        t = threading.Thread(target=release)
        t.start()
        # This dispatch fills the window (len == depth) and poll-waits on
        # the oldest until the release thread flips it ready.
        pipe.dispatch(make, steps=1)
        t.join()
        assert pipe.host_syncs == 0
        assert len(pipe) == 1

    def test_poll_deadline_degrades_to_counted_block(self):
        pipe = DispatchPipeline(2, probe_fn=lambda p: p,
                                poll_s=1e-4, poll_deadline_s=0.02)
        pipe.dispatch(lambda: _FakeProbe(ready=False), steps=1)
        # Fills the window; the oldest never turns ready, the deadline
        # blows, and the hard block is counted.
        pipe.dispatch(lambda: _FakeProbe(ready=False), steps=1)
        assert pipe.host_syncs == 1

    def test_sync_counts_one_event_per_burst(self):
        pipe = DispatchPipeline(8, probe_fn=lambda p: p)
        for _ in range(4):
            pipe.dispatch(lambda: _FakeProbe(ready=False), steps=1)
        assert pipe.sync() == 4
        assert pipe.host_syncs == 1       # one burst, one sync
        for _ in range(3):
            pipe.dispatch(lambda: _FakeProbe(ready=True), steps=1)
        pipe.drain_ready()
        assert pipe.sync() == 0           # nothing left -> free
        assert pipe.host_syncs == 1

    def test_gap_recorded_when_device_idles(self):
        gaps = _GapSink()
        pipe = DispatchPipeline(4, probe_fn=lambda p: p, gap_hist_ms=gaps)
        pipe.dispatch(lambda: _FakeProbe(ready=True), steps=1)
        time.sleep(0.02)
        pipe.dispatch(lambda: _FakeProbe(ready=False), steps=1)
        # Newest (the ready probe) had landed before this dispatch: idle.
        assert gaps.values and gaps.values[-1] >= 10.0  # ms
        pipe.dispatch(lambda: _FakeProbe(ready=False), steps=1)
        # Newest not ready -> device busy -> 0 gap.
        assert gaps.values[-1] == 0.0

    def test_steps_accounting_via_on_retire(self):
        seen = []
        pipe = DispatchPipeline(
            4, probe_fn=lambda p: p,
            on_retire=lambda m, s: seen.append(s),
        )
        for _ in range(6):
            pipe.dispatch(lambda: _FakeProbe(ready=True), steps=16)
        pipe.sync()
        assert sum(seen) == 96
        assert pipe.steps_inflight == 0


class TestOverlappedRuntime:
    def _cfg(self, depth, sync_every, steps):
        from ape_x_dqn_tpu.config import ApexConfig

        cfg = ApexConfig()
        cfg.network = "mlp"
        cfg.env.name = "random:8x8x1"
        cfg.actor.num_actors = 4
        cfg.actor.T = 1_000_000
        cfg.actor.flush_every = 8
        cfg.learner.device_replay = True
        cfg.learner.sample_ahead = True
        cfg.learner.steps_per_call = 32
        cfg.learner.ingest_block = 64
        cfg.learner.min_replay_mem_size = 128
        cfg.learner.publish_every = 128
        cfg.learner.total_steps = steps
        cfg.learner.pipeline_depth = depth
        cfg.learner.sync_every = sync_every
        cfg.replay.capacity = 2048
        return cfg.validate()

    def test_overlapped_fused_run_end_to_end(self):
        from ape_x_dqn_tpu.runtime.async_pipeline import AsyncPipeline
        from ape_x_dqn_tpu.utils.metrics import MetricLogger

        buf = io.StringIO()
        pipe = AsyncPipeline(
            self._cfg(depth=2, sync_every=64, steps=256),
            logger=MetricLogger(stream=buf), log_every=128,
        )
        final = pipe.run(learner_steps=256, warmup_timeout=120.0)
        assert final["step"] >= 256
        assert np.isfinite(final["learner/loss"])
        p = final["pipeline"]
        assert p["depth"] == 2 and p["sync_every"] == 64
        assert p["inflight"] == 0, "flush-at-exit left calls in flight"
        assert p["gaps_observed"] > 0
        # The JSONL stream carries the same section.
        lines = [json.loads(l) for l in buf.getvalue().splitlines()]
        periodic = [r for r in lines if "pipeline" in r]
        assert periodic, "pipeline section missing from the JSONL stream"
        # /varz carries the instruments.
        snap = pipe.obs_registry.snapshot()
        assert "learner/host_syncs" in snap
        assert "learner/overlap_gap_ms" in snap

    def test_host_path_batched_writeback(self):
        """pipeline_depth > 1 on the HOST-replay path batches the deferred
        priority write-back; the run completes and priorities were
        committed (replay priorities moved off the init value)."""
        from ape_x_dqn_tpu.config import ApexConfig
        from ape_x_dqn_tpu.runtime.async_pipeline import AsyncPipeline
        from ape_x_dqn_tpu.utils.metrics import MetricLogger

        cfg = ApexConfig()
        cfg.network = "mlp"
        cfg.env.name = "chain:6"
        cfg.actor.num_actors = 4
        cfg.actor.T = 1_000_000
        cfg.actor.flush_every = 8
        cfg.learner.min_replay_mem_size = 64
        cfg.learner.total_steps = 40
        cfg.learner.optimizer = "adam"
        cfg.learner.learning_rate = 1e-3
        cfg.learner.pipeline_depth = 4
        cfg.replay.capacity = 1024
        cfg.validate()
        pipe = AsyncPipeline(
            cfg, logger=MetricLogger(stream=io.StringIO()), log_every=1000,
        )
        final = pipe.run(learner_steps=40, warmup_timeout=120.0)
        assert final["step"] == 40
        assert np.isfinite(final["learner/loss"])
        # The final flush committed the tail: fewer than depth steps can
        # remain unwritten, and the tree total reflects restamps.
        assert pipe.comps.replay.size() > 0


class TestConfigKnobs:
    def test_validation(self):
        from ape_x_dqn_tpu.config import ApexConfig

        cfg = ApexConfig()
        cfg.learner.pipeline_depth = 0
        with pytest.raises(ValueError, match="pipeline_depth"):
            cfg.validate()
        cfg = ApexConfig()
        cfg.learner.sync_every = 64
        with pytest.raises(ValueError, match="sync_every"):
            cfg.validate()  # requires device_replay
        cfg.learner.device_replay = True
        cfg.validate()
