"""Env layer tests: synthetic envs, wrappers, vectorization (SURVEY §4)."""

import numpy as np
import pytest

from ape_x_dqn_tpu.envs import (
    CatchEnv,
    ChainMDP,
    FrameSkip,
    FrameStack,
    ObsPreprocess,
    RandomFrameEnv,
    RewardClip,
    StepResult,
    SyncVectorEnv,
    make_env,
)


class TestChainMDP:
    def test_optimal_rollout(self):
        env = ChainMDP(n_states=5)
        obs = env.reset()
        assert obs.argmax() == 0
        total, done = 0.0, False
        for _ in range(4):
            obs, r, done, trunc = env.step(1)
            total += r
        assert done and total == 1.0 and obs.argmax() == 4

    def test_left_clamps_and_truncates(self):
        env = ChainMDP(n_states=5, time_limit=3)
        env.reset()
        for i in range(3):
            obs, r, term, trunc = env.step(0)
        assert trunc and not term and obs.argmax() == 0


class TestCatch:
    def test_catch_and_miss(self):
        env = CatchEnv(rows=5, cols=3, seed=0)
        env.reset(seed=1)
        ball_col = int(np.argwhere(env._obs()[0, :, 0])[0])
        # Track the ball: move paddle toward ball_col each step.
        done, reward = False, 0.0
        while not done:
            paddle = env._paddle
            a = 1 + np.sign(ball_col - paddle)
            _, reward, done, _ = env.step(int(a))
        assert reward == 1.0

    def test_obs_has_two_pixels(self):
        env = CatchEnv()
        obs = env.reset(seed=0)
        assert (obs > 0).sum() in (1, 2)  # ball may overlap paddle column


class FakePixelEnv:
    """Deterministic raw RGB env for wrapper tests."""

    observation_shape = (10, 8, 3)
    num_actions = 2

    def __init__(self):
        self.t = 0

    def reset(self, seed=None):
        self.t = 0
        return np.full(self.observation_shape, 10, np.uint8)

    def step(self, action):
        self.t += 1
        obs = np.full(self.observation_shape, 10 * self.t % 250, np.uint8)
        return StepResult(obs, 1.0, self.t >= 6, False)


class TestWrappers:
    def test_obs_preprocess_resizes_and_grays(self):
        env = ObsPreprocess(FakePixelEnv(), height=4, width=4)
        obs = env.reset()
        assert obs.shape == (4, 4, 1) and obs.dtype == np.uint8

    def test_frame_skip_accumulates_reward(self):
        env = FrameSkip(FakePixelEnv(), skip=4)
        env.reset()
        r = env.step(0)
        assert r.reward == 4.0

    def test_frame_skip_stops_at_terminal(self):
        env = FrameSkip(FakePixelEnv(), skip=4)
        env.reset()
        env.step(0)  # t=4
        r = env.step(0)  # t=5,6 -> terminal at 6
        assert r.terminated and r.reward == 2.0

    def test_frame_stack(self):
        env = FrameStack(ObsPreprocess(FakePixelEnv(), 4, 4), k=3)
        obs = env.reset()
        assert obs.shape == (4, 4, 3)
        r = env.step(0)
        # Newest frame is last channel; oldest two still the reset frame.
        assert r.obs.shape == (4, 4, 3)

    def test_reward_clip(self):
        class BigReward(FakePixelEnv):
            def step(self, action):
                return super().step(action)._replace(reward=7.5)

        env = RewardClip(BigReward())
        env.reset()
        assert env.step(0).reward == 1.0


class TestVector:
    def test_lockstep_and_autoreset(self):
        envs = SyncVectorEnv([lambda: ChainMDP(4, time_limit=50)] * 3)
        obs = envs.reset(seed=0)
        assert obs.shape == (3, 4)
        # All go right: terminal after 3 steps.
        for t in range(3):
            vs = envs.step(np.ones(3, np.int64))
        assert vs.terminated.all()
        # Final obs is the terminal state; reset_obs is the fresh start.
        assert (vs.obs.argmax(-1) == 3).all()
        assert (vs.reset_obs.argmax(-1) == 0).all()
        assert np.allclose(vs.episode_return, 1.0)
        assert (vs.episode_length == 3).all()

    def test_episode_stats_nan_when_running(self):
        envs = SyncVectorEnv([lambda: ChainMDP(10)] * 2)
        envs.reset()
        vs = envs.step(np.ones(2, np.int64))
        assert np.isnan(vs.episode_return).all()

    def test_heterogeneous_rejected(self):
        with pytest.raises(ValueError):
            SyncVectorEnv([lambda: ChainMDP(4), lambda: ChainMDP(5)])


def test_make_env_specs():
    assert isinstance(make_env("chain:7"), ChainMDP)
    assert isinstance(make_env("catch"), CatchEnv)
    env = make_env("random:16x16x1")
    assert isinstance(env, RandomFrameEnv)
    assert env.observation_shape == (16, 16, 1)


class TestGymnasiumAdapter:
    """GymnasiumEnv / make_local_env (reference env.py:3-4's gym.make
    passthrough) against a real gymnasium env — the one adapter to external
    environments (round-2 verdict: previously zero coverage).  gymnasium is
    an optional dependency, so skip (not error) where it's absent."""

    @pytest.fixture(autouse=True)
    def _need_gymnasium(self):
        pytest.importorskip("gymnasium")

    def test_cartpole_protocol_roundtrip(self):
        from ape_x_dqn_tpu.envs import make_local_env

        env = make_local_env("CartPole-v1")
        assert env.num_actions == 2
        assert env.observation_shape == (4,)
        obs = env.reset(seed=0)
        assert obs.shape == (4,)
        saw_end = False
        for _ in range(600):  # CartPole-v1 truncates at 500
            r = env.step(1)
            assert r.obs.shape == (4,)
            assert isinstance(r.reward, float)
            assert isinstance(r.terminated, bool)
            assert isinstance(r.truncated, bool)
            if r.terminated or r.truncated:
                saw_end = True
                env.reset()
                break
        assert saw_end, "constant-action CartPole must terminate quickly"

    def test_cartpole_seeded_reset_reproducible(self):
        from ape_x_dqn_tpu.envs import make_local_env

        a = make_local_env("CartPole-v1").reset(seed=7)
        b = make_local_env("CartPole-v1").reset(seed=7)
        np.testing.assert_array_equal(a, b)

    def test_unwrapped_exposes_gym_env(self):
        from ape_x_dqn_tpu.envs import make_local_env

        env = make_local_env("CartPole-v1")
        assert hasattr(env.unwrapped, "action_space")


class TestQuantizeObs:
    def test_affine_map_and_clip(self):
        from ape_x_dqn_tpu.envs import QuantizeObs

        class FloatBoxEnv:
            observation_shape = (3,)
            num_actions = 2

            def reset(self, seed=None):
                return np.array([-1.0, 0.0, 99.0])  # 99 is out of bounds

            def step(self, action):
                return StepResult(np.array([1.0, -5.0, 0.5]), 0.0, False, False)

        env = QuantizeObs(FloatBoxEnv(), low=[-1, -1, -1], high=[1, 1, 1])
        obs = env.reset()
        assert obs.dtype == np.uint8
        np.testing.assert_array_equal(obs, [0, 128, 255])  # clip above
        r = env.step(0)
        np.testing.assert_array_equal(r.obs, [255, 0, 191])  # clip below

    def test_infinite_bounds_clamped(self):
        from ape_x_dqn_tpu.envs import make_gym_env

        env = make_gym_env("CartPole-v1", inf_bound=5.0)
        obs = env.reset(seed=0)
        assert obs.dtype == np.uint8 and obs.shape == (4,)

    def test_requires_bounds_without_box_space(self):
        from ape_x_dqn_tpu.envs import QuantizeObs

        with pytest.raises(ValueError, match="low/high"):
            QuantizeObs(ChainMDP())


class TestRealGymnasiumEndToEnd:
    """VERDICT r4 missing item 1: the GymnasiumEnv adapter driven by an
    ACTUALLY INSTALLED gymnasium env through the full stack — fleet (batched
    policy + n-step emission) -> prioritized replay -> learner train steps.
    ALE itself is not installable in this image (recorded below), so classic
    control is the real-env integration surface."""

    def test_ale_status_is_environmental(self):
        # The Atari gap is provably environmental, not a latent bug: the
        # adapter works (tests here), and ale_py simply isn't importable.
        import importlib.util

        assert importlib.util.find_spec("ale_py") is None, (
            "ale_py became importable — wire make_atari_env through it and "
            "drop this guard"
        )

    def test_cartpole_through_fleet_replay_learner(self):
        import jax
        import jax.numpy as jnp

        from ape_x_dqn_tpu.actors import ActorFleet, LocalParamSource
        from ape_x_dqn_tpu.envs import make_env
        from ape_x_dqn_tpu.learner.train_step import (
            build_train_step,
            init_train_state,
            make_optimizer,
        )
        from ape_x_dqn_tpu.models.dueling import DuelingMLP
        from ape_x_dqn_tpu.replay import PrioritizedReplay

        net = DuelingMLP(num_actions=2, hidden_sizes=(32,))
        fleet = ActorFleet(
            [lambda: make_env("gym:CartPole-v1")] * 4,
            net, n_step=3, gamma=0.99, flush_every=8, seed=3,
        )
        params = net.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.uint8))
        fleet.sync_params(LocalParamSource(params))
        replay = PrioritizedReplay(4096, (4,))
        chunks, stats = fleet.collect(64)
        assert chunks, "fleet emitted no chunks off real gymnasium envs"
        for c in chunks:
            replay.add(c.priorities, c.transitions)
        assert replay.size() >= 8 * 4
        # CartPole episodes end fast under a random-ish policy: episode
        # stats must flow through the vector autoreset path.
        assert stats, "no completed CartPole episodes in 64 fleet steps"

        opt = make_optimizer("adam", learning_rate=1e-3)
        state = init_train_state(
            net, opt, jax.random.PRNGKey(1), np.zeros((1, 4), np.uint8)
        )
        step = build_train_step(net, opt)
        for _ in range(5):
            batch = replay.sample(32, rng=np.random.default_rng(0))
            state, metrics = step(state, jax.device_put(batch))
            replay.update_priorities(
                batch.indices, np.asarray(metrics.priorities)
            )
        assert np.isfinite(np.asarray(metrics.loss))
        assert int(state.step) == 5


class TestPixelUpscale:
    def test_upscale_and_pad_geometry(self):
        from ape_x_dqn_tpu.envs import CatchEnv, PixelUpscale

        env = PixelUpscale(CatchEnv(seed=0), 84, 84)
        obs = env.reset(seed=0)
        assert obs.shape == (84, 84, 1) and obs.dtype == np.uint8
        # 10x5 board -> 8x16 integer blocks + zero pad: exactly two
        # lit rectangles (ball + paddle), each 8*16 pixels.
        assert (obs > 0).sum() == 2 * 8 * 16
        r = env.step(1)
        assert r.obs.shape == (84, 84, 1)
        assert env.num_actions == 3

    def test_target_smaller_than_source_rejected(self):
        from ape_x_dqn_tpu.envs import CatchEnv, PixelUpscale

        with pytest.raises(ValueError):
            PixelUpscale(CatchEnv(), 8, 8)

    def test_factory_spec(self):
        env = make_env("catch:32")
        assert env.reset(seed=1).shape == (32, 32, 1)
