"""FusedDedupLearner (runtime driver for the dedup HBM ring): stager
semantics, ingest scheduling, end-to-end equivalence with the double-store
fused runtime, sharded mode, and checkpoint/resume (verdict item 1a)."""

import jax
import numpy as np
import pytest

from ape_x_dqn_tpu.actors import ActorFleet, LocalParamSource
from ape_x_dqn_tpu.envs import CatchEnv
from ape_x_dqn_tpu.learner.train_step import init_train_state, make_optimizer
from ape_x_dqn_tpu.models.dueling import DuelingMLP
from ape_x_dqn_tpu.runtime.fused_dedup import DedupStager, FusedDedupLearner
from ape_x_dqn_tpu.types import DedupChunk

OBS = (10, 5, 1)


def build_parts(seed=0):
    net = DuelingMLP(num_actions=3, hidden_sizes=(16,))
    opt = make_optimizer("adam", learning_rate=1e-3)
    state = init_train_state(
        net, opt, jax.random.PRNGKey(seed), np.zeros((1, *OBS), np.uint8)
    )
    return net, opt, state


def collect_chunks(n_steps=64, num=4, dedup=True, seed=3, flush=8):
    net, _, state = build_parts()
    fleet = ActorFleet(
        [lambda: CatchEnv(seed=5)] * num, net, n_step=3, flush_every=flush,
        seed=seed, emit_dedup=dedup,
    )
    fleet.sync_params(LocalParamSource(state.params))
    chunks, _ = fleet.collect(n_steps)
    return chunks


class TestDedupStager:
    def chunk(self, src, seq, n_tx=4, carry=0, prev_frames=0, fbase=0):
        U = n_tx + 1
        frames = np.full((U, *OBS), (fbase + np.arange(U))[:, None, None, None]
                         % 251, np.uint8)
        return DedupChunk(
            frames=frames,
            obs_ref=np.concatenate([
                -np.arange(carry, 0, -1, dtype=np.int32),
                np.arange(n_tx, dtype=np.int32)]),
            next_ref=np.concatenate([
                np.zeros(carry, np.int32),
                np.arange(1, n_tx + 1, dtype=np.int32)]),
            action=np.zeros(n_tx + carry, np.int32),
            reward=np.zeros(n_tx + carry, np.float32),
            discount=np.ones(n_tx + carry, np.float32),
            source=src, chunk_seq=seq, prev_frames=prev_frames,
        )

    def test_sources_pin_to_shards_round_robin(self):
        st = DedupStager(n_shards=2)
        for src in (7, 8, 9):
            st.add_chunk(np.ones(4), self.chunk(src, 0))
        assert st.sources[7][0] == 0
        assert st.sources[8][0] == 1
        assert st.sources[9][0] == 0
        # Continuation chunks stay on the pinned shard.
        st.add_chunk(np.ones(6), self.chunk(7, 1, carry=2, prev_frames=5))
        assert st.sources[7][0] == 0
        assert st.dropped_carry == 0

    def test_txn_blocks_gate_on_shipped_frames(self):
        st = DedupStager(n_shards=1)
        st.add_chunk(np.ones(4), self.chunk(1, 0))
        # 5 frames staged, 4 txns staged; nothing shipped yet.
        assert st.frame_blocks_available(4) == 1
        assert st.txn_blocks_available(4) == 0, (
            "transitions must not ship before their frames"
        )
        _ = st.take_frame_block(4)  # ships frames 0-3; txns need frame 4
        assert st.txn_blocks_available(4) == 0
        _ = st.take_frame_block(1)
        assert st.txn_blocks_available(4) == 1
        blk = st.take_txn_block(4)
        assert blk["obs_seq"].shape == (1, 4)
        np.testing.assert_array_equal(blk["obs_seq"][0], [0, 1, 2, 3])
        np.testing.assert_array_equal(blk["next_seq"][0], [1, 2, 3, 4])

    def test_carry_gap_drops_carried_rows(self):
        st = DedupStager(n_shards=1)
        st.add_chunk(np.ones(4), self.chunk(1, 0))
        st.add_chunk(np.ones(6), self.chunk(1, 3, carry=2, prev_frames=5))
        assert st.dropped_carry == 2
        assert st.staged_rows == 8  # 4 + (6-2)

    def test_snapshot_roundtrip(self):
        st = DedupStager(n_shards=2)
        st.add_chunk(np.ones(4), self.chunk(1, 0))
        st.add_chunk(np.ones(4), self.chunk(2, 0))
        st.add_chunk(np.ones(6), self.chunk(1, 1, carry=2, prev_frames=5))
        _ = st.take_frame_block(2)
        snap = st.state_dict()
        st2 = DedupStager(n_shards=2)
        st2.load_state_dict(snap)
        assert st2.staged_rows == st.staged_rows
        assert st2.sources == st.sources
        assert [s.shipped_f for s in st2.shards] == [
            s.shipped_f for s in st.shards
        ]
        # The restored stager keeps shipping where the old one stopped.
        assert st2.frame_blocks_available(1) == st.frame_blocks_available(1)


class TestFusedDedupLearner:
    def make_learner(self, state=None, mesh=None, **kw):
        net, opt, st = build_parts()
        defaults = dict(
            capacity=2048, batch_size=8, steps_per_call=4, ingest_block=32,
            target_sync_freq=8, sample_ahead=False, frame_ratio=1.5,
        )
        defaults.update(kw)
        return FusedDedupLearner(
            net, opt, state if state is not None else st, OBS,
            mesh=mesh, **defaults,
        )

    def test_end_to_end_training(self):
        learner = self.make_learner()
        for c in collect_chunks(96):
            learner.add_chunk(c.priorities, c.transitions)
        n = learner.ingest_staged()
        assert n > 0 and learner.size == n
        for _ in range(3):
            metrics = learner.train(0.4)
        assert np.isfinite(np.asarray(metrics.loss)).all()
        assert learner.step == 12

    def test_rejects_dense_chunks(self):
        learner = self.make_learner()
        dense = collect_chunks(24, dedup=False)
        with pytest.raises(TypeError, match="DedupChunk"):
            learner.add_chunk(dense[0].priorities, dense[0].transitions)

    def test_matches_double_store_runtime(self):
        """Same actor stream into FusedDedupLearner and FusedDeviceLearner
        (dense twin), same rng → identical params and losses."""
        from ape_x_dqn_tpu.runtime.fused_learner import FusedDeviceLearner
        from ape_x_dqn_tpu.types import materialize_dedup

        net, opt, st_a = build_parts()
        _, _, st_b = build_parts()
        common = dict(
            capacity=2048, batch_size=8, steps_per_call=4, ingest_block=32,
            target_sync_freq=8,
        )
        a = FusedDedupLearner(net, opt, st_a, OBS, frame_ratio=2.0, **common)
        b = FusedDeviceLearner(net, opt, st_b, OBS, **common)
        chunks = collect_chunks(96)
        prev = None
        for c in chunks:
            a.add_chunk(c.priorities, c.transitions)
            b.add_chunk(c.priorities, materialize_dedup(c.transitions, prev))
            prev = c.transitions
        # drain=True on both: in steady (non-drain) mode the dedup stager
        # legitimately holds back transitions whose frame tail hasn't
        # shipped yet; a full drain makes the ring contents identical.
        na, nb = a.ingest_staged(drain=True), b.ingest_staged(drain=True)
        assert na == nb > 0
        for i in range(3):
            ma = a.train(0.4)
            mb = b.train(0.4)
            np.testing.assert_allclose(
                np.asarray(ma.loss), np.asarray(mb.loss), rtol=1e-6,
                err_msg=f"call {i}",
            )
        jax.tree_util.tree_map(
            lambda x, y: np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-6
            ),
            a.state.params, b.state.params,
        )

    def test_checkpoint_roundtrip_with_staged_rows(self):
        learner = self.make_learner()
        chunks = collect_chunks(96)
        for c in chunks[:-2]:
            learner.add_chunk(c.priorities, c.transitions)
        learner.ingest_staged()
        for _ in range(2):
            learner.train(0.4)
        # Stage more rows that DON'T align to a block: they must survive
        # the snapshot (no padding, no loss).
        for c in chunks[-2:]:
            learner.add_chunk(c.priorities, c.transitions)
        staged_before = learner.staged_rows
        snap = learner.state_dict()

        net, opt, st2 = build_parts(seed=9)
        restored = FusedDedupLearner(
            net, opt, st2, OBS, capacity=2048, batch_size=8,
            steps_per_call=4, ingest_block=32, target_sync_freq=8,
            frame_ratio=1.5,
        )
        restored.load_state_dict(snap)
        assert restored.staged_rows == staged_before
        assert restored.size == learner.size
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(restored._replay.mass)),
            np.asarray(snap["mass"]),
        )
        # The restored learner keeps training and ingesting.
        restored.ingest_staged(drain=True)
        m = restored.train(0.4)
        assert np.isfinite(np.asarray(m.loss)).all()

    def test_drain_ships_unaligned_tails(self):
        learner = self.make_learner(ingest_block=64)
        chunks = collect_chunks(40)  # 40 steps x 4 actors ≈ 132 rows
        for c in chunks:
            learner.add_chunk(c.priorities, c.transitions)
        n_full = learner.ingest_staged()
        n_drain = learner.ingest_staged(drain=True)
        assert n_drain > 0
        # After a drain, only frame-ineligible transitions may remain;
        # with all frames drained first, that's at most... 0.
        assert learner.staged_rows == 0, (
            "drain must ship every staged transition once its frames land"
        )
        assert learner.size == n_full + n_drain


class TestShardedFusedDedup:
    def test_sharded_mode_trains_and_checkpoints(self):
        from ape_x_dqn_tpu.parallel import make_mesh

        mesh = make_mesh(num_devices=4)
        net, opt, st = build_parts()
        learner = FusedDedupLearner(
            net, opt, st, OBS, capacity=4096, batch_size=8,
            steps_per_call=4, ingest_block=64, target_sync_freq=8,
            frame_ratio=1.5, mesh=mesh,
        )
        # 8 sources (fleet incarnations) spread over 4 shards.
        for s in range(8):
            for c in collect_chunks(48, num=2, seed=100 + s):
                learner.add_chunk(c.priorities, c.transitions)
        n = learner.ingest_staged()
        assert n > 0 and n % 4 == 0
        for _ in range(3):
            metrics = learner.train(0.4)
        assert np.isfinite(np.asarray(metrics.loss)).all()
        assert learner.step == 12
        snap = learner.state_dict()
        _, _, st2 = build_parts(seed=1)
        r2 = FusedDedupLearner(
            net, opt, st2, OBS, capacity=4096, batch_size=8,
            steps_per_call=4, ingest_block=64, target_sync_freq=8,
            frame_ratio=1.5, mesh=make_mesh(num_devices=4),
        )
        r2.load_state_dict(snap)
        assert r2.size == learner.size
        m = r2.train(0.4)
        assert np.isfinite(np.asarray(m.loss)).all()

    def test_shard_layout_mismatch_rejected(self):
        from ape_x_dqn_tpu.parallel import make_mesh

        net, opt, st = build_parts()
        learner = FusedDedupLearner(
            net, opt, st, OBS, capacity=4096, batch_size=8,
            steps_per_call=4, ingest_block=64, target_sync_freq=8,
            mesh=make_mesh(num_devices=4),
        )
        snap = learner.state_dict()
        _, _, st2 = build_parts(seed=1)
        two = FusedDedupLearner(
            net, opt, st2, OBS, capacity=4096, batch_size=8,
            steps_per_call=4, ingest_block=64, target_sync_freq=8,
            mesh=make_mesh(num_devices=2),
        )
        with pytest.raises(ValueError):
            two.load_state_dict(snap)
