"""Replay-as-a-service: the adversarial RPC matrix + degradation
contract (replay/service.py).

The replay plane inherits the experience transport's decode discipline —
torn/bitflipped/oversize/out-of-seq frames counted and NEVER decoded —
and adds the service-level contracts on top: stale-incarnation hello
rejection, per-request deadlines with whole-request retry, at-most-once
adds under lost replies, write-back buffering while a shard is down, and
restart-under-load recovery through the shard's own checkpoint chain.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time

import numpy as np
import pytest

from ape_x_dqn_tpu.replay.buffer import PrioritizedReplay
from ape_x_dqn_tpu.replay.service import (
    _RERR,
    _RPC,
    _SAMPLE_REQ,
    FLAG_DUP,
    OP_ADD,
    OP_DIGEST,
    OP_SAMPLE,
    RSVC_ACK,
    RSVC_ACK_MAGIC,
    RSVC_HELLO,
    RSVC_MAGIC,
    RSVC_VERSION,
    ReplayShardServer,
    ReplayShardUnavailable,
    ShardClient,
    ShardedReplayClient,
    decode_body,
    encode_body,
)
from ape_x_dqn_tpu.runtime.net import CODEC_OFF, CODEC_ZLIB, F_RREQ, frame_bytes

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OBS = (6,)


def _chunk(n=8, seed=0, overlap=False):
    r = np.random.default_rng(seed)
    obs = r.integers(0, 255, (n, *OBS), dtype=np.uint8)
    arrays = {
        "prio": (np.abs(r.normal(size=n)) + 0.1).astype(np.float64),
        "obs": obs,
        "action": r.integers(0, 2, n).astype(np.int32),
        "reward": r.normal(size=n).astype(np.float32),
        "discount": np.full(n, 0.99, np.float32),
        # n-step overlap shape: next_obs[i] == obs[i+1] — the dedup
        # encoder's target redundancy.
        "next_obs": (np.roll(obs, -1, axis=0) if overlap
                     else r.integers(0, 255, (n, *OBS), dtype=np.uint8)),
    }
    return arrays


class _Batch:
    def __init__(self, arrays):
        for k, v in arrays.items():
            if k != "prio":
                setattr(self, k, v)
        self.prio = arrays["prio"]


@pytest.fixture
def shard():
    rep = PrioritizedReplay(256, OBS, priority_exponent=0.6)
    srv = ReplayShardServer(rep, 0, incarnation=2, token=777,
                            codec="zlib").start()
    yield rep, srv
    srv.close()


def _client_for(srv, **kw):
    kw.setdefault("request_timeout_s", 5.0)
    return ShardedReplayClient(
        [{"id": 0, "host": "127.0.0.1", "port": srv.port, "base": 0,
          "capacity": srv.replay.capacity,
          "incarnation": srv.incarnation}],
        token=srv.token, **kw,
    )


def _raw_conn(srv, incarnation=None, token=None, codec=CODEC_ZLIB,
              client_id=9, flags=0):
    """Handshake a raw socket (returns it past the ack)."""
    s = socket.create_connection(("127.0.0.1", srv.port), timeout=5.0)
    s.sendall(RSVC_HELLO.pack(
        RSVC_MAGIC, RSVC_VERSION, client_id, srv.shard_id,
        srv.incarnation if incarnation is None else incarnation,
        srv.token if token is None else token, codec, flags,
    ))
    s.settimeout(5.0)
    ack = b""
    while len(ack) < RSVC_ACK.size:
        got = s.recv(RSVC_ACK.size - len(ack))
        if not got:
            s.close()
            return None
        ack += got
    assert RSVC_ACK.unpack(ack)[0] == RSVC_ACK_MAGIC
    return s


def _wait(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# Body codec.
# ---------------------------------------------------------------------------


class TestBodyCodec:
    def test_round_trip_bit_exact(self):
        arrays = _chunk(seed=1)
        body = encode_body(arrays, codec=CODEC_ZLIB, dedup=True)
        out = decode_body(body)
        for k, v in arrays.items():
            np.testing.assert_array_equal(out[k], v)

    def test_dedup_shrinks_overlapping_chunks(self):
        # Frames must be >= the dedup span floor (64 B) to dedup; (6,)
        # obs are below it, so use a frame-shaped chunk here.
        r = np.random.default_rng(3)
        obs = r.integers(0, 255, (16, 12, 12, 1), dtype=np.uint8)
        dense = {
            "prio": np.ones(16), "obs": obs,
            "action": np.zeros(16, np.int32),
            "reward": np.zeros(16, np.float32),
            "discount": np.ones(16, np.float32),
            "next_obs": np.roll(obs, -1, axis=0),
        }
        plain = encode_body(dense, codec=CODEC_OFF, dedup=False)
        deduped = encode_body(dense, codec=CODEC_OFF, dedup=True)
        assert len(deduped) < 0.7 * len(plain)
        out = decode_body(deduped)
        np.testing.assert_array_equal(out["next_obs"], dense["next_obs"])

    def test_malformed_bodies_raise(self):
        body = encode_body(_chunk(), codec=CODEC_OFF, dedup=False)
        with pytest.raises(ValueError):
            decode_body(body[:len(body) // 2])
        with pytest.raises(ValueError):
            decode_body(bytes((9,)) + body[1:])      # unknown codec byte
        zbody = encode_body(_chunk(), codec=CODEC_ZLIB, dedup=False)
        if zbody[0] == 1:  # compressed payload on an off-codec connection
            with pytest.raises(ValueError):
                decode_body(zbody, allow_zlib=False)


# ---------------------------------------------------------------------------
# Adversarial frames against a live shard.
# ---------------------------------------------------------------------------


class TestShardAdversarial:
    def test_truncated_request_frame_torn_never_applied(self, shard):
        rep, srv = shard
        s = _raw_conn(srv)
        payload = _RPC.pack(1, OP_ADD) + encode_body(_chunk())
        frame = frame_bytes(F_RREQ, 1, [payload])
        s.sendall(frame[:len(frame) - 7])     # cut mid-payload
        s.close()                             # disconnect mid-frame
        _wait(lambda: srv.torn_frames >= 1, msg="torn count")
        assert rep.total_added == 0           # never decoded, never applied
        assert srv.ops["add"] == 0

    def test_bitflipped_request_frame_torn(self, shard):
        rep, srv = shard
        s = _raw_conn(srv)
        payload = _RPC.pack(1, OP_ADD) + encode_body(_chunk())
        frame = bytearray(frame_bytes(F_RREQ, 1, [payload]))
        frame[40] ^= 0x10                     # flip a payload byte: crc fails
        s.sendall(bytes(frame))
        _wait(lambda: srv.torn_frames >= 1, msg="crc torn")
        assert rep.total_added == 0
        s.close()

    def test_oversize_prefix_torn(self, shard):
        _rep, srv = shard
        s = _raw_conn(srv)
        # A length prefix past max_request_bytes must fail BEFORE the
        # server buffers it.
        s.sendall(struct.pack("<IIqB7x", (1 << 30) + 5, 0, 1, F_RREQ))
        _wait(lambda: srv.torn_frames >= 1, msg="oversize torn")
        s.close()

    def test_out_of_seq_frame_torn(self, shard):
        _rep, srv = shard
        s = _raw_conn(srv)
        payload = _RPC.pack(1, OP_DIGEST)
        s.sendall(frame_bytes(F_RREQ, 3, [payload]))   # seq must start at 1
        _wait(lambda: srv.torn_frames >= 1, msg="seq torn")
        s.close()

    def test_wrong_kind_frame_torn(self, shard):
        _rep, srv = shard
        from ape_x_dqn_tpu.runtime.net import F_RREP

        s = _raw_conn(srv)
        s.sendall(frame_bytes(F_RREP, 1, [b"x"]))      # replies never flow in
        _wait(lambda: srv.torn_frames >= 1, msg="kind torn")
        s.close()

    def test_bad_hello_rejected_before_framing(self, shard):
        _rep, srv = shard
        assert _raw_conn(srv, token=123456) is None    # wrong run token
        _wait(lambda: srv.bad_hellos >= 1, msg="bad hello")
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=5.0)
        s.sendall(b"GARBAGEGARBAGEGARBAGEGARBAGEGARBAGEGARBAGEJUNK!!")
        _wait(lambda: srv.bad_hellos >= 2, msg="garbage hello")
        s.close()
        assert srv.torn_frames == 0           # rejected pre-framing

    def test_stale_incarnation_hello_rejected(self, shard):
        _rep, srv = shard
        assert _raw_conn(srv, incarnation=srv.incarnation - 1) is None
        _wait(lambda: srv.stale_rejects >= 1, msg="stale reject")
        assert _raw_conn(srv, incarnation=-1) is not None  # "current" ok

    def test_well_framed_garbage_is_typed_not_torn(self, shard):
        rep, srv = shard
        s = _raw_conn(srv)
        s.sendall(frame_bytes(F_RREQ, 1,
                              [_RPC.pack(7, OP_ADD) + b"\x00garbage"]))
        deadline = time.monotonic() + 5.0
        buf = b""
        while time.monotonic() < deadline and len(buf) < 24:
            buf += s.recv(1 << 16)
        # A typed F_RERR reply came back; the stream is NOT torn.
        assert srv.errors >= 1
        assert srv.torn_frames == 0
        assert rep.total_added == 0
        s.close()

    def test_bitflipped_reply_frame_torn_client_side(self, shard):
        """A corrupted REPLY stream is dropped client-side (counted on
        rpc_torn) and the request retries on a fresh connection."""
        rep, srv = shard
        # Seed the shard so samples answer.
        cl = _client_for(srv)
        cl.add(_chunk()["prio"], _Batch(_chunk(seed=5)))
        cl.close()

        # Man-in-the-middle proxy that flips one byte of the first reply.
        lsock = socket.socket()
        lsock.bind(("127.0.0.1", 0))
        lsock.listen(4)
        pport = lsock.getsockname()[1]
        flipped = threading.Event()

        def proxy():
            while True:
                try:
                    a, _ = lsock.accept()
                except OSError:
                    return
                b = socket.create_connection(("127.0.0.1", srv.port))

                def pump(src, dst, corrupt):
                    try:
                        while True:
                            d = src.recv(1 << 16)
                            if not d:
                                break
                            if corrupt and not flipped.is_set() \
                                    and len(d) > RSVC_ACK.size + 40:
                                d = bytearray(d)
                                d[RSVC_ACK.size + 30] ^= 0x40
                                d = bytes(d)
                                flipped.set()
                            dst.sendall(d)
                    except OSError:
                        pass
                    for x in (src, dst):
                        try:
                            x.close()
                        except OSError:
                            pass

                threading.Thread(target=pump, args=(a, b, False),
                                 daemon=True).start()
                threading.Thread(target=pump, args=(b, a, True),
                                 daemon=True).start()

        t = threading.Thread(target=proxy, daemon=True)
        t.start()
        sc = ShardClient(0, "127.0.0.1", pport, token=srv.token,
                         client_id=31, incarnation=-1)
        _flags, rep_body = sc.request(
            OP_SAMPLE, _SAMPLE_REQ.pack(4, 0.4, 17), timeout=15.0
        )
        assert rep_body                       # answered despite the flip
        assert flipped.is_set()
        assert sc.torn >= 1 or sc.reconnects >= 1
        sc.close()
        lsock.close()


# ---------------------------------------------------------------------------
# Retry discipline + at-most-once adds.
# ---------------------------------------------------------------------------


class _ScriptedChaos:
    """Drop exactly the scripted requests (deterministic lost-reply)."""

    def __init__(self, drops):
        self._drops = list(drops)

    def delay_s(self):
        return 0.0

    def drop(self):
        return self._drops.pop(0) if self._drops else False


class TestRetryAndIdempotence:
    def test_deadline_expiry_is_typed(self):
        cl = ShardClient(0, "127.0.0.1", 1, token=1, client_id=1)
        t0 = time.monotonic()
        with pytest.raises(ReplayShardUnavailable) as ei:
            cl.request(OP_DIGEST, timeout=0.6)
        assert time.monotonic() - t0 < 5.0
        assert ei.value.shard_id == 0 and ei.value.op == "digest"
        cl.close()

    def test_drop_then_retry_applies_exactly_once(self):
        rep = PrioritizedReplay(256, OBS)
        srv = ReplayShardServer(rep, 0, token=5,
                                chaos=_ScriptedChaos([True]))
        srv.start()
        try:
            sc = ShardClient(0, "127.0.0.1", srv.port, token=5,
                             client_id=3, io_timeout_s=0.5)
            arrays = _chunk(seed=9)
            body = encode_body(arrays, codec=CODEC_ZLIB)
            # First send is dropped shard-side (no reply) → the io
            # timeout forces a whole-request retry with the SAME req_id.
            flags, rep_body = sc.request(OP_ADD, body, timeout=20.0)
            assert sc.retries >= 1
            assert rep.total_added == 8       # applied exactly once
            assert srv.chaos_dropped == 1
            sc.close()
        finally:
            srv.close()

    def test_duplicate_add_served_from_cache(self, shard):
        rep, srv = shard
        sc = ShardClient(0, "127.0.0.1", srv.port, token=srv.token,
                         client_id=4)
        body = encode_body(_chunk(seed=11), codec=CODEC_ZLIB)
        rid = sc.next_req_id()
        flags1, rep1 = sc.request(OP_ADD, body, req_id=rid)
        flags2, rep2 = sc.request(OP_ADD, body, req_id=rid)   # replay it
        assert flags1 == 0 and flags2 == FLAG_DUP
        assert rep1 == rep2                   # byte-identical cached reply
        assert rep.total_added == 8           # at-most-once
        assert srv.add_dups == 1
        sc.close()

    def test_backoff_resets_only_on_verified_reply(self, shard):
        _rep, srv = shard
        sc = ShardClient(0, "127.0.0.1", 1, token=srv.token, client_id=5)
        with pytest.raises(ReplayShardUnavailable):
            sc.request(OP_DIGEST, timeout=0.8)
        fails_after_dead = sc._backoff._fails
        assert fails_after_dead >= 1
        sc.host, sc.port = "127.0.0.1", srv.port
        sc._backoff.reset()   # endpoint re-resolve resets pacing
        sc.request(OP_DIGEST, timeout=5.0)
        assert sc._backoff._fails == 0


# ---------------------------------------------------------------------------
# Fleet client degradation: down-shard routing, write-back buffering,
# recovery flush, stale-incarnation re-resolve.
# ---------------------------------------------------------------------------


class TestShardedDegradation:
    def _two_shards(self, tmp_path=None):
        reps = [PrioritizedReplay(128, OBS) for _ in range(2)]
        srvs = [ReplayShardServer(r, k, incarnation=0, token=99)
                for k, r in enumerate(reps)]
        for s in srvs:
            s.start()
        cl = ShardedReplayClient(
            [{"id": k, "host": "127.0.0.1", "port": s.port, "base": 128 * k,
              "capacity": 128, "incarnation": 0}
             for k, s in enumerate(srvs)],
            token=99, request_timeout_s=1.5, probe_interval_s=0.2,
        )
        return reps, srvs, cl

    def test_survivor_keeps_serving_and_writebacks_flush(self):
        reps, srvs, cl = self._two_shards()
        try:
            # Fill both shards.
            for seed in range(6):
                cl.add(_chunk(seed=seed)["prio"], _Batch(_chunk(seed=seed)))
            batch = cl.sample(8, rng=np.random.default_rng(0))
            assert cl.size() == reps[0].size() + reps[1].size()

            # Kill shard 1 (its slot range is [128, 256)).
            port1 = srvs[1].port
            srvs[1].close()
            idx1 = np.arange(130, 138)
            cl.update_priorities(idx1, np.full(8, 9.0))
            _wait(lambda: 1 in cl._down or cl.stats()["writeback_pending"],
                  msg="shard 1 marked down")
            st = cl.stats()
            assert st["writeback_pending"] >= 1
            assert st["degraded"] and st["shards_down"] == 1

            # Sampling and adding keep working against the survivor.
            for _ in range(4):
                b = cl.sample(8, rng=np.random.default_rng(1))
                assert b.indices.max() < 128   # survivor's range only
            idx = cl.add(_chunk(seed=31)["prio"], _Batch(_chunk(seed=31)))
            assert idx.max() < 128

            # Respawn shard 1 on the SAME port with a fresh incarnation;
            # the probe must flush the parked write-backs, then recover.
            srvs[1] = ReplayShardServer(reps[1], 1, incarnation=1,
                                        token=99, port=port1).start()
            cl._clients[1].set_endpoint("127.0.0.1", port1, 1)
            _wait(lambda: not cl.degraded, msg="recovery")
            st = cl.stats()
            assert st["writeback_pending"] == 0
            assert st["writeback_flushed"] >= 8
            assert st["recoveries"] >= 1
            # Last-write-wins landed: mass at the written slots moved.
            m = reps[1]._tree.get(np.arange(2, 10))
            np.testing.assert_allclose(m, 9.0 ** 0.6, rtol=1e-9)
            del batch
        finally:
            cl.close()
            for s in srvs:
                s.close()

    def test_all_down_is_typed(self):
        reps, srvs, cl = self._two_shards()
        try:
            cl.add(_chunk()["prio"], _Batch(_chunk()))
            for s in srvs:
                s.close()
            with pytest.raises(ReplayShardUnavailable):
                for _ in range(3):
                    cl.sample(4, rng=np.random.default_rng(2))
            assert cl.degraded and cl.age_s() >= 0.0
        finally:
            cl.close()
            for s in srvs:
                s.close()

    def test_stale_incarnation_reresolves_via_endpoints_file(self, tmp_path):
        rep = PrioritizedReplay(128, OBS)
        srv = ReplayShardServer(rep, 0, incarnation=0, token=7).start()
        ep = tmp_path / "endpoints.json"

        def write_ep(port, inc):
            doc = {"token": 7, "codec": "zlib", "total_capacity": 128,
                   "shards": [{"id": 0, "host": "127.0.0.1", "port": port,
                               "base": 0, "capacity": 128,
                               "incarnation": inc}]}
            tmp = str(ep) + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, str(ep))

        write_ep(srv.port, 0)
        cl = ShardedReplayClient.from_endpoints_file(
            str(ep), request_timeout_s=1.5, probe_interval_s=0.15,
        )
        try:
            cl.add(_chunk()["prio"], _Batch(_chunk()))
            # "Respawn" the shard: new incarnation, new port; the pinned
            # old incarnation would be rejected even if the port matched.
            old_port = srv.port
            srv.close()
            srv = ReplayShardServer(rep, 0, incarnation=1, token=7).start()
            assert srv.port != old_port or True
            # Drive the client into the down state.
            with pytest.raises(ReplayShardUnavailable):
                cl.sample(4, rng=np.random.default_rng(0))
            write_ep(srv.port, 1)
            time.sleep(0.05)               # distinct mtime granularity
            os.utime(str(ep))
            _wait(lambda: not cl.degraded, msg="re-resolve + recovery")
            b = cl.sample(4, rng=np.random.default_rng(1))
            assert len(b.indices) == 4
            assert cl._clients[0].incarnation == 1
        finally:
            cl.close()
            srv.close()

    def test_empty_fleet_sample_raises_value_error(self):
        reps, srvs, cl = self._two_shards()
        try:
            with pytest.raises(ValueError):
                cl.sample(4, rng=np.random.default_rng(0))
        finally:
            cl.close()
            for s in srvs:
                s.close()


# ---------------------------------------------------------------------------
# Shard persistence: digest-verified chain recovery.
# ---------------------------------------------------------------------------


class TestShardRecovery:
    def test_chain_restore_is_bit_exact_by_digest(self, tmp_path, shard):
        rep, srv = shard
        del srv
        from ape_x_dqn_tpu.utils.checkpoint_inc import (
            IncrementalCheckpointer,
            load_incremental_replay,
        )

        ck = IncrementalCheckpointer(str(tmp_path), rep, sync=True)
        for seed in range(4):
            rep.add(_chunk(seed=seed)["prio"],
                    _Batch(_chunk(seed=seed)))
            ck.save(rep.total_added)
        want = rep.digest(with_crc=True)
        fresh = PrioritizedReplay(256, OBS)
        step = load_incremental_replay(str(tmp_path), fresh, fallback=True)
        assert step == rep.total_added
        got = fresh.digest(with_crc=True)
        assert got == want                    # bit-exact recovery

    def test_corrupt_chain_recovery_is_typed_or_exact(self, tmp_path, shard):
        rep, _srv = shard
        from ape_x_dqn_tpu.obs.chaos import corrupt_chunk, pick_chunk
        from ape_x_dqn_tpu.utils.checkpoint_inc import (
            IncrementalCheckpointer,
            load_incremental_replay,
        )

        ck = IncrementalCheckpointer(str(tmp_path), rep, sync=True)
        digests = []
        for seed in range(4):
            rep.add(_chunk(seed=seed)["prio"], _Batch(_chunk(seed=seed)))
            ck.save(rep.total_added)
            digests.append(rep.digest(with_crc=True))
        inc = os.path.join(str(tmp_path), "replay_inc")
        path = pick_chunk(inc, prefer="delta")
        corrupt_chunk(path, "bitflip")
        events = []
        fresh = PrioritizedReplay(256, OBS)
        step = load_incremental_replay(
            str(tmp_path), fresh, fallback=True,
            on_event=events.append,
        )
        # Walked back to SOME committed rung — and that rung is bit-exact
        # against the digest recorded when it was live.
        assert any(e["event"] == "degraded_restore" for e in events)
        got = fresh.digest(with_crc=True)
        assert got in digests
        assert got["count"] == step


# ---------------------------------------------------------------------------
# RpcChaos determinism + config plumbing.
# ---------------------------------------------------------------------------


class TestChaosPlumbing:
    def test_rpc_chaos_is_seed_deterministic(self):
        from ape_x_dqn_tpu.obs.chaos import RpcChaos

        a = RpcChaos(delay_ms=4.0, drop_rate=0.3, seed=11)
        b = RpcChaos(delay_ms=4.0, drop_rate=0.3, seed=11)
        sa = [(round(a.delay_s(), 9), a.drop()) for _ in range(64)]
        sb = [(round(b.delay_s(), 9), b.drop()) for _ in range(64)]
        assert sa == sb
        assert a.drops > 0

    def test_chaos_config_validation(self):
        from ape_x_dqn_tpu.config import ApexConfig

        cfg = ApexConfig()
        cfg.chaos.rpc_drop_rate = 1.5
        with pytest.raises(ValueError):
            cfg.validate()

    def test_service_config_validation(self):
        from ape_x_dqn_tpu.config import ApexConfig

        cfg = ApexConfig()
        cfg.replay.service_mode = "attach"
        with pytest.raises(ValueError):      # endpoints required
            cfg.validate()
        cfg.replay.service_endpoints = "x.json"
        cfg.validate()
        cfg.replay.dedup = True
        with pytest.raises(ValueError):      # dedup stays learner-local
            cfg.validate()
        cfg.replay.dedup = False
        cfg.learner.checkpoint_incremental = True
        cfg.learner.checkpoint_every = 100
        with pytest.raises(ValueError):      # shards own the chains
            cfg.validate()

    def test_remote_worker_config_validation(self):
        from ape_x_dqn_tpu.config import ApexConfig

        cfg = ApexConfig()
        cfg.actor.remote_workers = 2
        with pytest.raises(ValueError):      # needs process+tcp
            cfg.validate()
        cfg.actor.mode = "process"
        cfg.actor.transport = "tcp"
        with pytest.raises(ValueError):      # needs a join path
            cfg.validate()
        cfg.actor.remote_join_path = "join.json"
        cfg.actor.num_actors = 5
        cfg.actor.num_workers = 2
        cfg.validate()

    def test_monkey_schedules_kill_shard(self):
        from ape_x_dqn_tpu.config import ChaosConfig
        from ape_x_dqn_tpu.obs.chaos import ChaosMonkey

        m = ChaosMonkey(ChaosConfig(enabled=True, seed=4,
                                    kill_shard_interval_s=5.0))
        kinds = {k for _, k in m.schedule}
        assert kinds == {"kill_shard"}
        # Unattached: the kind degrades to a skipped record, not a crash.
        rec = m.execute("kill_shard")
        assert rec["skipped"]


# ---------------------------------------------------------------------------
# Schema pin.
# ---------------------------------------------------------------------------


def _doc_keys(section_header):
    # Shared parser (apexlint satellite): one implementation in
    # ape_x_dqn_tpu/analysis/metrics_doc.py serves every schema pin.
    from ape_x_dqn_tpu.analysis.metrics_doc import doc_section_keys

    return doc_section_keys(
        section_header, os.path.join(REPO, "docs", "METRICS.md"))


class TestReplaySvcDocSchema:
    def test_client_stats_match_doc(self, shard):
        _rep, srv = shard
        doc = _doc_keys("## Replay service schema")
        assert doc, "Replay service schema doc section missing"
        cl = _client_for(srv)
        try:
            st = cl.stats()
            assert set(doc) == set(st), sorted(set(doc) ^ set(st))
        finally:
            cl.close()


# ---------------------------------------------------------------------------
# Restart-under-load barrage: subprocess shards + live traffic + kills.
# ---------------------------------------------------------------------------


class TestRestartUnderLoad:
    def test_barrage(self, tmp_path):
        from ape_x_dqn_tpu.replay.service import ReplayServiceFleet

        fleet = ReplayServiceFleet(
            2, 512, OBS, root_dir=str(tmp_path), save_every_s=0.5,
            respawn_base_s=0.1, respawn_max_s=0.5,
        )
        fleet.start(timeout=60.0)
        cl = ShardedReplayClient.from_endpoints_file(
            fleet.endpoints_path, request_timeout_s=3.0,
            probe_interval_s=0.15,
        )
        errors = []
        stop = threading.Event()

        def traffic():
            r = np.random.default_rng(0)
            seed = 0
            while not stop.is_set():
                seed += 1
                try:
                    idx = cl.add(_chunk(seed=seed)["prio"],
                                 _Batch(_chunk(seed=seed)))
                    b = cl.sample(8, rng=r)
                    cl.update_priorities(
                        b.indices, np.abs(r.normal(size=8)) + 0.1
                    )
                    del idx
                except (ReplayShardUnavailable, ValueError):
                    time.sleep(0.01)    # typed degradation: keep going
                except Exception as e:  # noqa: BLE001 — anything else fails
                    errors.append(e)
                    return

        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        try:
            _wait(lambda: cl.adds >= 5, timeout=30.0, msg="traffic flowing")
            for round_ in range(2):
                victim = round_ % 2
                fleet.kill(victim)
                _wait(lambda: fleet.shards[victim].alive()
                      and fleet.shards[victim].port is not None,
                      timeout=60.0, msg="respawn")
                _wait(lambda: not cl.degraded, timeout=60.0,
                      msg="client recovery")
            _wait(lambda: cl.adds >= 10, timeout=30.0, msg="traffic resumed")
        finally:
            stop.set()
            t.join(timeout=30.0)
            st = cl.stats()
            cl.close()
            fleet.stop()
        assert not errors, errors
        assert st["rpc_torn"] == 0            # clean streams throughout
        assert fleet.respawns >= 2
        assert st["recoveries"] >= 1
        # Respawned shards recovered from their chains: both report a
        # listen event with a restored_step on their second incarnation.
        for sid in (0, 1):
            evs = [e for e in fleet.shards[sid].events
                   if e.get("event") == "replay_shard_listen"
                   and e.get("incarnation", 0) >= 1]
            assert evs, f"shard {sid} second incarnation never announced"
