"""Fused device replay × data parallelism (replay/device_dp.py).

Round-3 verdict top item: the two fast paths must combine.  These tests run
on the conftest's 8 virtual CPU devices and pin the sharded semantics
against single-device oracles:

  * ingest splits chunks contiguously over shards' rings;
  * the per-shard sampler's indices and IS weights match a numpy
    inverse-CDF oracle of the realized sampling law q = (m_i/M_s)/n;
  * the strict-PER fused scan (sample → train with grad all-reduce →
    restamp, K steps) matches a hand-run emulation built from the
    single-device sample/update functions + a concatenated-batch train
    step — params AND per-shard restamped masses;
  * the async pipeline runs end-to-end in fused+DP mode;
  * checkpoints round-trip the sharded ring (with staged rows).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ape_x_dqn_tpu.learner.train_step import (
    build_train_step,
    init_train_state,
    make_optimizer,
)
from ape_x_dqn_tpu.models.dueling import DuelingMLP
from ape_x_dqn_tpu.parallel import make_mesh
from ape_x_dqn_tpu.replay.device import (
    DeviceReplayState,
    device_replay_sample,
    device_replay_sample_many,
    device_replay_update_priorities,
)
from ape_x_dqn_tpu.replay.device_dp import (
    _local,
    build_sharded_fused_learn_step,
    build_sharded_replay_add,
    init_sharded_device_replay,
    replay_specs,
)
from ape_x_dqn_tpu.runtime.fused_learner import FusedDeviceLearner
from ape_x_dqn_tpu.types import NStepTransition, PrioritizedBatch


def np_chunk(M, obs_shape=(8,), seed=0):
    r = np.random.default_rng(seed)
    return NStepTransition(
        obs=r.integers(0, 255, (M, *obs_shape), dtype=np.uint8),
        action=r.integers(0, 3, (M,), dtype=np.int32),
        reward=r.normal(size=(M,)).astype(np.float32),
        discount=np.full((M,), 0.9, np.float32),
        next_obs=r.integers(0, 255, (M, *obs_shape), dtype=np.uint8),
    )


class TestShardedIngest:
    def test_chunk_splits_contiguously_over_shards(self):
        n, C = 4, 64  # C_local = 16
        mesh = make_mesh(num_devices=n)
        state = init_sharded_device_replay(C, (8,), mesh)
        add = build_sharded_replay_add(mesh)
        chunk = np_chunk(32, seed=1)
        state = add(state, jax.device_put(chunk), jnp.ones(32))
        got = jax.device_get(state)
        # Shard d's ring occupies global rows [d*16, (d+1)*16); its first 8
        # slots hold chunk rows [d*8, (d+1)*8).
        for d in range(n):
            np.testing.assert_array_equal(
                got.obs[d * 16: d * 16 + 8], chunk.obs[d * 8: (d + 1) * 8]
            )
        np.testing.assert_array_equal(np.asarray(got.cursor), [8] * n)
        np.testing.assert_array_equal(np.asarray(got.count), [8] * n)

    def test_capacity_must_divide(self):
        mesh = make_mesh(num_devices=4)
        with pytest.raises(ValueError, match="divide"):
            init_sharded_device_replay(30, (8,), mesh)


def _manual_global_state(mesh, n, C_local, mass_global):
    """A FULL sharded ring with given integer masses and arbitrary rows."""
    C = n * C_local
    chunk = np_chunk(C, seed=7)
    state = init_sharded_device_replay(C, (8,), mesh)
    add = build_sharded_replay_add(mesh)
    # Priorities whose ^0.6 mass we overwrite below; rows land contiguous.
    state = add(state, jax.device_put(chunk), jnp.ones(C))
    state = state.replace(
        mass=jax.device_put(
            jnp.asarray(mass_global, jnp.float32), state.mass.sharding
        )
    )
    return state, chunk


class TestShardedSampler:
    def test_indices_and_weights_match_numpy_oracle(self):
        """The realized per-shard law is q_i = (m_i / M_s) / n; indices come
        from a stratified inverse-CDF over the shard's mass and weights are
        (N_global · q_i)^-β normalized by the GLOBAL batch max."""
        n, C_local, K, B = 4, 16, 3, 8
        beta = 0.7
        mesh = make_mesh(num_devices=n)
        r = np.random.default_rng(3)
        # Integer masses -> exact float32 prefix sums -> bit-exact oracle.
        mass = r.integers(1, 50, n * C_local).astype(np.float32)
        state, _ = _manual_global_state(mesh, n, C_local, mass)
        rng = jax.random.PRNGKey(11)

        def run(st, key):
            def body(st_l):
                loc = _local(st_l)
                k = jax.random.fold_in(key, jax.lax.axis_index("data"))
                b = device_replay_sample_many(
                    loc, k, K, B, beta, axis_name="data"
                )
                return b.indices, b.is_weights

            from jax.sharding import PartitionSpec as P

            from ape_x_dqn_tpu.parallel.mesh import shard_map

            return shard_map(
                body, mesh=mesh, in_specs=(replay_specs(),),
                out_specs=(P(None, "data"), P(None, "data")),
            )(st)

        idx_g, w_g = jax.device_get(run(state, rng))  # [K, n*B] each

        # ---- numpy oracle ----
        N_global = n * C_local  # every slot filled
        want_idx = np.zeros((K, n * B), np.int64)
        raw_w = np.zeros((K, n * B), np.float64)
        for s in range(n):
            m_s = mass[s * C_local:(s + 1) * C_local]
            total = np.float32(m_s.sum())
            u = np.asarray(
                jax.random.uniform(jax.random.fold_in(rng, s), (K, B))
            )
            targets = (
                (np.arange(B, dtype=np.float32)[None, :] + u)
                * (total / np.float32(B))
            ).astype(np.float32)
            targets = np.minimum(targets, total * np.float32(1.0 - 1e-7))
            cdf = np.cumsum(m_s, dtype=np.float32)
            idx = np.searchsorted(cdf, targets, side="right")
            idx = np.clip(idx, 0, C_local - 1)
            q = m_s[idx] / total / n
            want_idx[:, s * B:(s + 1) * B] = idx
            raw_w[:, s * B:(s + 1) * B] = (N_global * q) ** (-beta)
        want_w = raw_w / raw_w.max(axis=1, keepdims=True)

        np.testing.assert_array_equal(idx_g, want_idx)
        np.testing.assert_allclose(w_g, want_w, rtol=1e-5)


class TestShardedFusedStrict:
    def test_matches_concat_batch_emulation(self):
        """The whole strict-PER fused call — K × [per-shard sample → train
        with pmean'd grads → per-shard restamp] — against an emulation
        from single-device pieces: per-shard sampling with hand-computed
        global IS weights, ONE train step on the concatenated global batch,
        per-shard priority updates.  Params and restamped masses agree."""
        n, C_local, K, B_local = 2, 32, 3, 4
        B = n * B_local
        pexp, beta = 0.6, 0.5
        mesh = make_mesh(num_devices=n)
        r = np.random.default_rng(5)
        mass = r.integers(1, 30, n * C_local).astype(np.float32)
        state_g, chunk = _manual_global_state(mesh, n, C_local, mass)

        net = DuelingMLP(num_actions=3, hidden_sizes=(16,))
        # Plain SGD: linear in the gradient, so emulation mismatches surface
        # as-is instead of being amplified to ±lr by RMSProp's rsqrt(nu≈0)
        # (first steps of rmsprop are ~sign(g) — float noise flips signs).
        # Debugged at K=1: loss/priorities agree to 1e-7 under rmsprop too.
        import optax

        opt = optax.sgd(1e-3)
        t0 = init_train_state(
            net, opt, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.uint8)
        )
        rng = jax.random.PRNGKey(42)

        # --- sharded run ---
        from jax.sharding import NamedSharding, PartitionSpec as P

        step_sh = build_train_step(
            net, opt, loss_kind="huber", sync_in_step=False,
            grad_reduce_axis="data", jit=False,
        )
        fused = build_sharded_fused_learn_step(
            step_sh, mesh, B, steps_per_call=K,
            priority_exponent=pexp, target_sync_freq=None,
        )
        t_repl = jax.jit(lambda s: s, out_shardings=NamedSharding(mesh, P()))(t0)
        t_f, r_f, metrics = fused(t_repl, state_g, beta, rng)
        got_params = jax.device_get(t_f.params)
        got_mass = np.asarray(jax.device_get(r_f.mass))

        # --- emulation ---
        step_em = build_train_step(
            net, opt, loss_kind="huber", sync_in_step=False, jit=False,
        )
        locals_ = []
        for s in range(n):
            sl = slice(s * C_local, (s + 1) * C_local)
            locals_.append(DeviceReplayState(
                obs=jnp.asarray(chunk.obs[sl]),
                next_obs=jnp.asarray(chunk.next_obs[sl]),
                action=jnp.asarray(chunk.action[sl], jnp.int32),
                reward=jnp.asarray(chunk.reward[sl]),
                discount=jnp.asarray(chunk.discount[sl]),
                mass=jnp.asarray(mass[sl]),
                cursor=jnp.zeros((), jnp.int32),
                count=jnp.asarray(C_local, jnp.int32),
            ))
        rngs = [jax.random.split(jax.random.fold_in(rng, s), K)
                for s in range(n)]
        t_em = t0
        N_global = float(n * C_local)
        for k in range(K):
            parts, idxs = [], []
            for s in range(n):
                b = device_replay_sample(locals_[s], rngs[s][k], B_local, beta)
                parts.append(jax.device_get(b))
                idxs.append(np.asarray(b.indices))
            # Correct the IS weights to the sharded law (the single-ring
            # sampler normalized per-shard with local N).
            raw = []
            for s in range(n):
                m_s = np.asarray(locals_[s].mass)
                q = m_s[idxs[s]] / m_s.sum() / n
                raw.append((N_global * q) ** (-beta))
            wmax = max(float(w.max()) for w in raw)
            weights = np.concatenate([w / wmax for w in raw]).astype(np.float32)
            batch = PrioritizedBatch(
                transition=NStepTransition(
                    obs=np.concatenate([p.transition.obs for p in parts]),
                    action=np.concatenate([p.transition.action for p in parts]),
                    reward=np.concatenate([p.transition.reward for p in parts]),
                    discount=np.concatenate(
                        [p.transition.discount for p in parts]
                    ),
                    next_obs=np.concatenate(
                        [p.transition.next_obs for p in parts]
                    ),
                ),
                indices=np.concatenate(idxs).astype(np.int32),
                is_weights=weights,
            )
            t_em, m_em = step_em(t_em, jax.device_put(batch))
            prios = np.asarray(m_em.priorities)
            for s in range(n):
                locals_[s] = device_replay_update_priorities(
                    locals_[s], jnp.asarray(idxs[s]),
                    jnp.asarray(prios[s * B_local:(s + 1) * B_local]), pexp,
                )

        want_params = jax.device_get(t_em.params)
        for a, b in zip(jax.tree_util.tree_leaves(got_params),
                        jax.tree_util.tree_leaves(want_params)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
            )
        want_mass = np.concatenate(
            [np.asarray(l.mass) for l in locals_]
        )
        np.testing.assert_allclose(got_mass, want_mass, rtol=1e-5, atol=1e-7)
        # The scan really ran K steps and losses were finite.
        assert int(jax.device_get(t_f.step)) == K
        assert np.isfinite(np.asarray(metrics.loss)).all()


class TestFusedDPRuntime:
    def test_pipeline_end_to_end(self):
        from ape_x_dqn_tpu.config import ApexConfig
        from ape_x_dqn_tpu.runtime.async_pipeline import AsyncPipeline

        cfg = ApexConfig()
        cfg.env.name = "chain:6"
        cfg.network = "mlp"
        cfg.actor.num_actors = 4
        cfg.actor.flush_every = 8
        cfg.learner.device_replay = True
        cfg.learner.data_parallel = 4
        cfg.learner.steps_per_call = 8
        cfg.learner.min_replay_mem_size = 128
        cfg.learner.replay_sample_size = 16
        cfg.learner.max_grad_norm = None
        cfg.replay.capacity = 2048
        pipe = AsyncPipeline(cfg, log_every=32)
        out = pipe.run(learner_steps=64, warmup_timeout=120)
        assert out["step"] >= 64
        assert np.isfinite(out["learner/loss"])
        assert out["replay_size"] >= 128

    def test_capacity_divisibility_validated(self):
        from ape_x_dqn_tpu.config import ApexConfig

        cfg = ApexConfig()
        cfg.learner.device_replay = True
        cfg.learner.data_parallel = 4
        cfg.replay.capacity = 100_002
        with pytest.raises(ValueError, match="capacity must be divisible"):
            cfg.validate()


class TestShardedSnapshot:
    def test_roundtrip_with_staged_rows(self):
        net = DuelingMLP(num_actions=3, hidden_sizes=(16,))
        opt = make_optimizer("adam", learning_rate=1e-3)
        mesh = make_mesh(num_devices=4)

        def make(seed):
            st = init_train_state(
                net, opt, jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.uint8)
            )
            return FusedDeviceLearner(
                net, opt, st, (8,), capacity=256, batch_size=16,
                steps_per_call=4, ingest_block=32, mesh=mesh,
            )

        fl = make(0)
        fl.add_chunk(np.ones(64, np.float32), np_chunk(64, seed=1))
        fl.ingest_staged()
        # 10 staged rows: 8 drain via the granularity decomposition, 2 stay
        # staged (< n shards) — the snapshot must carry them anyway.
        fl.add_chunk(np.ones(10, np.float32), np_chunk(10, seed=2))
        fl.ingest_staged(drain=True)
        assert fl.size == 72 and fl.staged_rows == 2
        fl.train(beta=0.4)
        sd = fl.state_dict()
        assert len(sd["staged_prio"]) == 2

        fl2 = make(9)
        fl2.load_state_dict(sd)
        assert fl2.size == 72 and fl2.staged_rows == 2
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(fl2._replay.mass)),
            np.asarray(jax.device_get(fl._replay.mass)),
        )
        m = fl2.train(beta=0.4)
        assert np.isfinite(np.asarray(m.loss)).all()


class TestSampleAheadRestampCollisions:
    def test_last_wins_per_shard_against_emulation(self):
        """Round-4 verdict item 7: sample-ahead restamps under dp>1.  Tiny
        per-shard rings force heavy duplicate sampling across the K
        batches; the final masses must equal a per-shard LAST-WINS
        emulation over the metrics' own (indices, priorities) — and no
        shard's restamp may touch another shard's rows (indices are
        shard-local by construction; global metrics columns group by
        shard)."""
        n, C_local, K, B_local = 4, 8, 6, 4
        mesh = make_mesh(num_devices=n)
        r = np.random.default_rng(3)
        mass = r.integers(1, 20, n * C_local).astype(np.float32)
        state_g, _ = _manual_global_state(mesh, n, C_local, mass)
        pre_mass = np.asarray(jax.device_get(state_g.mass)).copy()

        net = DuelingMLP(num_actions=3, hidden_sizes=(16,))
        import optax

        opt = optax.sgd(1e-3)
        t0 = init_train_state(
            net, opt, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.uint8)
        )
        step_fn = build_train_step(
            net, opt, sync_in_step=False, grad_reduce_axis="data", jit=False
        )
        pexp = 0.6
        fused = build_sharded_fused_learn_step(
            step_fn, mesh, n * B_local, steps_per_call=K,
            priority_exponent=pexp, target_sync_freq=None,
            sample_ahead=True,
        )
        _, state_g, metrics = fused(t0, state_g, 0.5, jax.random.PRNGKey(7))
        prios = np.asarray(jax.device_get(metrics.priorities))  # [K, B]
        post = np.asarray(jax.device_get(state_g.mass))
        # Recover each shard's sampled indices by re-running the SAME
        # sampler on the shard's pre-call ring slice with the same
        # folded rng (sample-ahead draws every batch from call-entry
        # masses, so this is exact).
        idx = np.zeros((K, n * B_local), np.int64)
        for s in range(n):
            local = DeviceReplayState(
                obs=jnp.zeros((C_local, 8), jnp.uint8),
                next_obs=jnp.zeros((C_local, 8), jnp.uint8),
                action=jnp.zeros((C_local,), jnp.int32),
                reward=jnp.zeros((C_local,), jnp.float32),
                discount=jnp.zeros((C_local,), jnp.float32),
                mass=jnp.asarray(
                    pre_mass[s * C_local:(s + 1) * C_local]
                ),
                cursor=jnp.int32(0),
                count=jnp.int32(C_local),
            )
            b = device_replay_sample_many(
                local, jax.random.fold_in(jax.random.PRNGKey(7), s),
                K, B_local, 0.5,
            )
            idx[:, s * B_local:(s + 1) * B_local] = np.asarray(b.indices)
        expect = pre_mass.copy()
        # Columns [s*B_local, (s+1)*B_local) belong to shard s; index
        # values are shard-LOCAL slots.
        for s in range(n):
            cols = slice(s * B_local, (s + 1) * B_local)
            for k in range(K):
                for j_local, p in zip(idx[k, cols], prios[k, cols]):
                    g = s * C_local + int(j_local)
                    expect[g] = np.power(max(float(p), 1e-12), pexp)
        np.testing.assert_allclose(post, expect, rtol=1e-6)
        # Cross-shard isolation: rows outside each shard's sampled set
        # keep their pre-call mass.
        touched = set()
        for s in range(n):
            cols = slice(s * B_local, (s + 1) * B_local)
            touched |= {
                s * C_local + int(j) for j in idx[:, cols].reshape(-1)
            }
        untouched = [g for g in range(n * C_local) if g not in touched]
        np.testing.assert_allclose(
            post[untouched], pre_mass[untouched], rtol=0
        )


class TestAwkwardIngestMidScan:
    def test_odd_chunks_interleaved_with_trains_lose_nothing(self):
        """Ingest chunks of sizes coprime to the shard count arrive BETWEEN
        fused calls (the runtime's real cadence); exact-row accounting must
        hold across drains and a mid-stream checkpoint restore."""
        net = DuelingMLP(num_actions=3, hidden_sizes=(16,))
        opt = make_optimizer("adam", learning_rate=1e-3)
        mesh = make_mesh(num_devices=4)

        def make(seed):
            st = init_train_state(
                net, opt, jax.random.PRNGKey(seed),
                jnp.zeros((1, 8), jnp.uint8),
            )
            return FusedDeviceLearner(
                net, opt, st, (8,), capacity=512, batch_size=16,
                steps_per_call=2, ingest_block=32, mesh=mesh,
            )

        fl = make(0)
        staged_total = 0
        sizes = [37, 51, 64, 7, 129, 3, 40]  # mostly coprime to 4
        for i, m in enumerate(sizes[:4]):
            fl.add_chunk(np.ones(m, np.float32), np_chunk(m, seed=i))
            staged_total += m
        fl.ingest_staged()
        fl.train(beta=0.4)
        fl.ingest_staged(drain=True)
        # Mid-scan snapshot (staged remainder < 4 rows rides along).
        sd = fl.state_dict()
        assert fl.size + fl.staged_rows == staged_total
        fl2 = make(1)
        fl2.load_state_dict(sd)
        assert fl2.size + fl2.staged_rows == staged_total
        for i, m in enumerate(sizes[4:]):
            fl2.add_chunk(
                np.ones(m, np.float32), np_chunk(m, seed=10 + i)
            )
            staged_total += m
            fl2.train(beta=0.4)
            fl2.ingest_staged(drain=(i == 2))
        assert fl2.size + fl2.staged_rows == staged_total
        assert fl2.staged_rows < 4  # everything drainable drained
        m = fl2.train(beta=0.4)
        assert np.isfinite(np.asarray(m.loss)).all()
