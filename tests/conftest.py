"""Test harness: force an 8-device virtual CPU platform.

This is the TPU analogue of "test multi-node without a real cluster"
(SURVEY §4): pjit/shard_map sharding and collectives run on 8 fake host
devices, so every distributed-semantics test runs anywhere.
Must run before jax initializes its backends, hence env vars at import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the outer env points at the TPU tunnel
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The container's sitecustomize registers the axon TPU plugin at interpreter
# start and pins jax_platforms=axon, so the env var alone is not enough —
# override via jax.config before any backend initializes.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)
