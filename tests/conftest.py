"""Test harness: force an 8-device virtual CPU platform.

This is the TPU analogue of "test multi-node without a real cluster"
(SURVEY §4): pjit/shard_map sharding and collectives run on 8 fake host
devices, so every distributed-semantics test runs anywhere.
Must run before jax initializes its backends, hence env vars at import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the outer env points at the TPU tunnel
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The container's sitecustomize registers the axon TPU plugin at interpreter
# start and pins jax_platforms=axon, so the env var alone is not enough —
# override via jax.config before any backend initializes.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture
def rng():
    return np.random.default_rng(0)


# ---------------------------------------------------------------------------
# Session-scoped transport-resource leak guard.
#
# The process-actor transport budget (256 workers × one shm ring + one
# control-queue pipe pair each; config.transport_budget) is only
# trustworthy if every exit path — clean stop, salvage-and-respawn,
# SIGKILL barrage, bench teardown — releases its /dev/shm segments and
# fds.  This fixture snapshots both at session start and asserts nothing
# leaked by session end, so any new test that strands a segment or a pipe
# fails the suite instead of silently eroding the fleet budget.
#
# Scoped to THIS session's segments: every segment the repo creates is
# named through runtime/shm_ring.session_shm_name, which embeds the
# APEX_SHM_SESSION token pinned below (children inherit it through the
# environment).  Concurrent pytest sessions or unrelated shm tooling on
# the same host no longer false-positive the guard — only segments
# carrying our own token count.
# ---------------------------------------------------------------------------

import secrets as _secrets

_SHM_TOKEN = _secrets.token_hex(4)
os.environ["APEX_SHM_SESSION"] = _SHM_TOKEN
_SHM_PREFIX = f"apx{_SHM_TOKEN}_"


def _shm_segments():
    try:
        return {
            n for n in os.listdir("/dev/shm")
            if n.startswith(_SHM_PREFIX)
        }
    except OSError:  # no /dev/shm on this platform — guard is a no-op
        return None


def _pipe_fds():
    """Count of pipe/FIFO fds held by THIS process (mp.Queue costs a pipe
    pair; a leaked queue shows up here long before ulimit does)."""
    import stat

    n = 0
    try:
        for fd in os.listdir("/proc/self/fd"):
            try:
                if stat.S_ISFIFO(os.stat(f"/proc/self/fd/{fd}").st_mode):
                    n += 1
            except OSError:  # fd closed between listdir and stat
                continue
    except OSError:  # no /proc — guard is a no-op
        return -1
    return n


@pytest.fixture(scope="session", autouse=True)
def transport_leak_guard():
    base_shm = _shm_segments()
    base_pipes = _pipe_fds()
    yield
    import gc

    gc.collect()  # drop test-local rings/queues awaiting finalizers
    if base_shm is not None:
        leaked = _shm_segments() - base_shm
        assert not leaked, (
            f"leaked /dev/shm segments after the suite: {sorted(leaked)} — "
            "some exit path skipped ShmRing.unlink()/SharedParamBuffer "
            "teardown"
        )
    if base_pipes >= 0:
        now = _pipe_fds()
        # Slack for lazily-created singletons (mp resource_tracker's pipe,
        # logging handlers); a single leaked mp.Queue costs 2+ fds per
        # worker so real leaks clear this bar immediately.
        assert now <= base_pipes + 6, (
            f"pipe-fd growth over the suite: {base_pipes} -> {now} — a "
            "control queue was not closed on some pool exit path"
        )
