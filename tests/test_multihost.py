"""Multi-host (cross-process SPMD) tests: two OS processes, one global mesh.

The TPU-pod execution model without pod hardware: each subprocess brings 4
virtual CPU devices, ``jax.distributed`` stitches them into one 8-device
global mesh, and the UNMODIFIED sharded train step (parallel/dp.py) trains
with its gradient all-reduce crossing the process boundary (gloo/gRPC
standing in for ICI/DCN).  This is the round-2 verdict's "multi-host seam"
demonstrated end to end, not just advertised.
"""

import socket
import subprocess
import sys
from pathlib import Path

import numpy as np

CHILD = Path(__file__).parent / "_multihost_child.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_children(mode: str, n: int = 2) -> dict:
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, str(CHILD), str(pid), str(n), str(port), mode],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for pid in range(n)
    ]
    results = {}
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, f"child failed:\n{out}\n{err[-2000:]}"
        for line in out.splitlines():
            if line.startswith("RESULT"):
                parts = line.split()
                results[int(parts[1])] = tuple(float(x) for x in parts[2:])
    assert set(results) == set(range(n)), results
    return results


def test_two_process_global_mesh_trains_in_lockstep():
    results = _run_children("step")
    # SPMD: every process computed the IDENTICAL replicated loss and step —
    # the all-reduce really synchronized them across the process boundary.
    (l0, s0), (l1, s1) = results[0], results[1]
    assert s0 == s1 == 3
    np.testing.assert_allclose(l0, l1, rtol=0, atol=0)


def test_two_process_async_pipeline_end_to_end():
    """The whole runtime under multi-host SPMD: per-host actors + replay,
    global batch assembly, DCN all-reduce, per-host priority writeback —
    params bit-identical across hosts after 60 learner steps."""
    results = _run_children("pipeline")
    (loss0, step0, dig0), (loss1, step1, dig1) = results[0], results[1]
    assert step0 == step1 >= 60
    assert np.isfinite(loss0) and np.isfinite(loss1)
    # The all-reduced params stayed in lockstep despite per-host data.
    np.testing.assert_allclose(dig0, dig1, rtol=0, atol=0)
