"""End-to-end integration + learning tests (SURVEY §4 levels 2-3).

Level 2: fake env + actors + replay + learner for a few iterations, asserting
replay contents and loss finiteness.  Level 3: the chain MDP trained to the
optimal policy in seconds on CPU."""

import numpy as np
import pytest

from ape_x_dqn_tpu.config import ApexConfig
from ape_x_dqn_tpu.runtime.single_process import SingleProcessDriver, beta_schedule


def tiny_config(**kw) -> ApexConfig:
    cfg = ApexConfig()
    cfg.env.name = kw.pop("env_name", "chain:6")
    cfg.network = "mlp"
    cfg.actor.num_actors = 4
    cfg.actor.num_steps = 3
    cfg.actor.flush_every = 8
    cfg.actor.sync_every = 32
    cfg.actor.gamma = 0.9
    cfg.learner.min_replay_mem_size = 200
    cfg.learner.replay_sample_size = 32
    cfg.learner.total_steps = 1000
    cfg.learner.q_target_sync_freq = 50
    cfg.learner.publish_every = 5
    cfg.learner.learning_rate = 3e-3
    cfg.learner.optimizer = "adam"
    cfg.replay.capacity = 5000
    for k, v in kw.items():
        setattr(cfg, k, v)
    return cfg.validate()


def test_integration_replay_fills_and_loss_finite():
    driver = SingleProcessDriver(tiny_config())
    results = driver.run(learner_steps=20)
    assert driver.replay.size() >= 200
    losses = [r.loss for r in results if np.isfinite(r.loss)]
    assert len(losses) >= 20
    assert all(np.isfinite(l) for l in losses)
    # Actor steps flowed: replay contents are real uint8 one-hots.
    batch = driver.replay.sample(16, rng=np.random.default_rng(0))
    assert batch.transition.obs.dtype == np.uint8
    assert set(np.unique(batch.transition.obs)) <= {0, 255}
    assert batch.transition.action.max() < 2


def test_beta_anneals_to_one():
    assert beta_schedule(0, 100, 0.4) == pytest.approx(0.4)
    assert beta_schedule(50, 100, 0.4) == pytest.approx(0.7)
    assert beta_schedule(100, 100, 0.4) == pytest.approx(1.0)
    assert beta_schedule(200, 100, 0.4) == pytest.approx(1.0)


def test_param_publication_reaches_actors():
    driver = SingleProcessDriver(tiny_config())
    v0 = driver.fleet.param_version
    driver.run(learner_steps=40)
    assert driver.fleet.param_version > v0


def test_chain_mdp_learns_optimal_policy():
    """The learning test: 6-state chain, optimal policy is always-right.
    After training, the greedy policy from every state must be 'right', and
    Q(start, right) must approximate gamma^(n-2).  γ=0.8 keeps the
    Q(s0, right) vs Q(s0, left) gap wide (0.41 vs 0.33) so the test is
    robust to minor value error."""
    cfg = tiny_config()
    cfg.actor.gamma = 0.8
    cfg.learner.q_target_sync_freq = 25
    driver = SingleProcessDriver(cfg, learner_steps_per_iter=4)
    driver.run(learner_steps=1500)
    n = 6
    states = np.eye(n, dtype=np.uint8) * 255
    q = driver.greedy_q_values(states)
    # Greedy action is 'right' everywhere except the (unreachable-as-input)
    # terminal state n-1.
    assert (q[: n - 1].argmax(axis=1) == 1).all(), f"greedy actions: {q.argmax(1)}"
    # Value of 'right' at the start state: gamma^(distance-1) * 1.
    expected = 0.8 ** (n - 2)
    assert q[0, 1] == pytest.approx(expected, abs=0.15), q[0]


def test_truncation_unbiased_value_sync():
    """LoopEnv pays +1/step and ends only by time limit; with truncation
    bootstrapping the value fixed point is 1/(1−γ) = 10.  Collapsing
    truncation into termination drags Q toward the mean remaining-horizon
    return (≲ 6.5 at γ=0.9, T=10) — assert we converge near the unbiased
    fixed point instead (VERDICT r2 item 5)."""
    cfg = tiny_config(env_name="loop:10")
    cfg.actor.gamma = 0.9
    cfg.learner.loss = "squared"
    cfg.learner.q_target_sync_freq = 25
    driver = SingleProcessDriver(cfg, learner_steps_per_iter=4)
    driver.run(learner_steps=2000)
    q = driver.greedy_q_values(np.full((1, 4), 255, np.uint8))
    assert q.max() > 8.5, f"Q biased toward truncation cutoff: {q}"
    assert q.max() < 12.0, f"Q diverged: {q}"


def test_mismatched_config_shapes_rejected():
    cfg = tiny_config()
    cfg.env.state_shape = (9, 9)
    with pytest.raises(ValueError, match="state_shape"):
        SingleProcessDriver(cfg)
    cfg = tiny_config()
    cfg.env.action_dim = 7
    with pytest.raises(ValueError, match="action_dim"):
        SingleProcessDriver(cfg)
