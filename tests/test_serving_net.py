"""Network serving tier tests: the request/reply codec + the socket
server's adversarial decode matrix (the serving mirror of
tests/test_net_transport.py — torn frames typed, never decoded,
connection retired), health-aware routing (503 drain / recovery
re-entry / dead-replica failover with client retry), the socket param
source against a real hub, and the APXC param-tail fallback chain."""

import json
import socket
import threading
import time
from concurrent.futures import Future
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from ape_x_dqn_tpu.runtime.net import (
    E_BAD_REQUEST,
    E_OVERLOADED,
    F_SERR,
    F_SREP,
    F_SREQ,
    FRAME,
    FrameParser,
    decode_error,
    decode_reply,
    decode_request,
    encode_error,
    encode_reply,
    encode_request,
    frame_bytes,
    serve_hello_bytes,
)
from ape_x_dqn_tpu.serving.batcher import ServedAction, ServerOverloaded
from ape_x_dqn_tpu.serving.net_server import ServingClient, ServingNetServer
from ape_x_dqn_tpu.serving.router import ServingRouter
from ape_x_dqn_tpu.serving.sources import (
    ParamTailSource,
    ParamTailWriter,
    parse_hub_spec,
)


class StubPolicy:
    """PolicyServer stand-in: instant completed futures, no jax."""

    def __init__(self, num_actions: int = 4, version: int = 7):
        self.param_version = version
        self.served = 0
        self.fail_with = None        # exception to raise from submit

    def submit(self, obs) -> Future:
        if self.fail_with is not None:
            raise self.fail_with
        f = Future()
        self.served += 1
        f.set_result(ServedAction(
            int(np.asarray(obs).sum()) % 4,
            np.arange(4, dtype=np.float32),
            self.param_version, 0.0,
        ))
        return f


@pytest.fixture
def net_server():
    srv = ServingNetServer(StubPolicy()).start()
    yield srv
    srv.close()


def _raw_conn(port: int, hello: bytes = None) -> socket.socket:
    s = socket.create_connection(("127.0.0.1", port), timeout=5.0)
    s.sendall(serve_hello_bytes() if hello is None else hello)
    return s


def _wait(cond, timeout=5.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(f"timeout waiting for {msg}")


class TestCodec:
    def test_request_roundtrip(self):
        obs = np.random.default_rng(0).integers(
            0, 255, (84, 84, 1), dtype=np.uint8
        )
        rid, back = decode_request(encode_request(123, obs))
        assert rid == 123
        np.testing.assert_array_equal(back, obs)

    def test_reply_roundtrip(self):
        q = np.arange(6, dtype=np.float32) * 0.5
        rid, action, version, back = decode_reply(
            encode_reply(9, 3, 42, q)
        )
        assert (rid, action, version) == (9, 3, 42)
        np.testing.assert_array_equal(back, q)

    def test_error_roundtrip(self):
        rid, code, msg = decode_error(
            encode_error(5, E_OVERLOADED, "queue full")
        )
        assert (rid, code, msg) == (5, E_OVERLOADED, "queue full")

    def test_shape_mismatch_typed(self):
        payload = bytearray(encode_request(1, np.zeros((4, 4), np.uint8)))
        with pytest.raises(ValueError, match="shape"):
            decode_request(bytes(payload[:-1]))   # one body byte short

    def test_bad_dtype_code_typed(self):
        payload = bytearray(encode_request(1, np.zeros(4, np.uint8)))
        payload[9] = 99                           # dtype code field
        with pytest.raises(ValueError, match="dtype"):
            decode_request(bytes(payload))


class TestServerAdversarial:
    """The decode matrix against a LIVE socket server: every framing
    fault is counted torn, nothing reaches the batcher, and the
    connection is retired."""

    def _req_frame(self, seq=1, rid=1):
        return frame_bytes(F_SREQ, seq,
                           [encode_request(rid, np.zeros(8, np.uint8))])

    def test_truncation_mid_prefix(self, net_server):
        s = _raw_conn(net_server.port)
        s.sendall(self._req_frame()[:FRAME.size - 3])
        s.close()
        _wait(lambda: net_server.torn_frames == 1, msg="torn count")
        assert net_server.requests == 0

    def test_truncation_mid_payload(self, net_server):
        s = _raw_conn(net_server.port)
        s.sendall(self._req_frame()[:FRAME.size + 5])
        s.close()
        _wait(lambda: net_server.torn_frames == 1, msg="torn count")
        assert net_server.requests == 0

    def test_crc_bitflip_retires_connection(self, net_server):
        buf = bytearray(self._req_frame())
        buf[FRAME.size + 4] ^= 0x10
        s = _raw_conn(net_server.port)
        s.sendall(bytes(buf))
        _wait(lambda: net_server.torn_frames == 1, msg="torn count")
        assert net_server.requests == 0
        # Connection retired: the peer observes EOF.
        s.settimeout(5.0)
        assert s.recv(64) == b""
        s.close()

    def test_oversize_length_prefix_rejected(self, net_server):
        s = _raw_conn(net_server.port)
        # Within the transport's GiB sanity cap but over the serving
        # plane's max_request_bytes — rejected BEFORE buffering it.
        s.sendall(FRAME.pack(64 << 20, 0, 1, F_SREQ))
        _wait(lambda: net_server.torn_frames == 1, msg="torn count")
        assert net_server.requests == 0
        s.settimeout(5.0)
        assert s.recv(64) == b""
        s.close()

    def test_wrong_kind_is_protocol_violation(self, net_server):
        s = _raw_conn(net_server.port)
        s.sendall(frame_bytes(F_SREP, 1, [b"client-sent-a-reply"]))
        _wait(lambda: net_server.torn_frames == 1, msg="torn count")
        assert net_server.requests == 0
        s.close()

    def test_bad_hello_rejected_before_framing(self, net_server):
        s = _raw_conn(net_server.port, hello=b"GET / HT")
        s.settimeout(5.0)
        assert s.recv(64) == b""
        _wait(lambda: net_server.bad_hellos == 1, msg="bad hello")
        assert net_server.torn_frames == 0
        s.close()

    def test_seq_skip_detected(self, net_server):
        s = _raw_conn(net_server.port)
        s.sendall(self._req_frame(seq=1, rid=1))
        s.sendall(self._req_frame(seq=3, rid=2))
        _wait(lambda: net_server.torn_frames == 1, msg="torn count")
        # The first (verified) request WAS served; the skip retired the
        # stream before the second could be decoded.
        assert net_server.requests == 1
        s.close()

    def test_well_framed_bad_request_is_typed_not_torn(self, net_server):
        bad = bytearray(encode_request(7, np.zeros(8, np.uint8)))
        bad[9] = 99                                # dtype code
        s = _raw_conn(net_server.port)
        s.sendall(frame_bytes(F_SREQ, 1, [bytes(bad)]))
        _wait(lambda: net_server.errors == 1, msg="typed error")
        assert net_server.torn_frames == 0
        # The connection SURVIVES (it framed correctly): an error reply
        # comes back and a follow-up request still works.
        p = FrameParser()
        s.settimeout(5.0)
        while True:
            got = p.next()
            if got is not None:
                break
            p.feed(s.recv(4096))
        kind, payload = got
        assert kind == F_SERR
        assert decode_error(payload)[1] == E_BAD_REQUEST
        s.sendall(self._req_frame(seq=2, rid=8))
        _wait(lambda: net_server.requests == 1, msg="follow-up served")
        s.close()

    def test_shed_is_typed_reply(self, net_server):
        net_server._server.fail_with = ServerOverloaded("full")
        c = ServingClient("127.0.0.1", net_server.port)
        with pytest.raises(ServerOverloaded):
            c.act(np.zeros(8, np.uint8), timeout=5.0)
        assert net_server.shed == 1
        c.close()

    def test_stats_schema_stable(self, net_server):
        keys = set(net_server.stats())
        assert {"port", "connections", "requests", "replies", "shed",
                "torn_frames", "bytes_in", "bytes_out", "param_version",
                "latency"} <= keys


class TestClientRetry:
    def test_roundtrip_and_latency(self, net_server):
        c = ServingClient("127.0.0.1", net_server.port)
        r = c.act(np.ones((4, 4), np.uint8), timeout=5.0)
        assert r.param_version == 7
        assert r.latency_s < 5.0
        assert c.retries == 0
        c.close()

    def test_client_survives_server_restart(self):
        policy = StubPolicy()
        srv = ServingNetServer(policy).start()
        c = ServingClient("127.0.0.1", srv.port)
        assert c.act(np.zeros(4, np.uint8), timeout=5.0).action >= 0
        srv.close()                      # connection dies under the client
        srv2 = ServingNetServer(policy).start()
        c.port = srv2.port               # "router" moved the backend
        r = c.act(np.zeros(4, np.uint8), timeout=30.0)
        assert r.param_version == 7
        assert c.reconnects >= 1
        c.close()
        srv2.close()


class _HealthStub:
    """Toggleable /healthz endpoint (the obs exporter stand-in)."""

    def __init__(self):
        stub = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):  # noqa: N802
                pass

            def do_GET(self):  # noqa: N802
                body = json.dumps({"status": "ok" if stub.ok else "bad"})
                code = 200 if stub.ok else 503
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body.encode())

        self.ok = True
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self._httpd.daemon_threads = True
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self._httpd.server_address[1]}/healthz"

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()


class TestRouter:
    """Health-aware routing over in-process stub replicas: real sockets,
    real /healthz probes, no subprocesses (the subprocess e2e lives in
    tools/serving_net_smoke.py, verify gate 9)."""

    def _fleet(self, n=2):
        replicas = []
        for i in range(n):
            policy = StubPolicy(version=i + 1)
            srv = ServingNetServer(policy).start()
            health = _HealthStub()
            replicas.append((policy, srv, health))
        router = ServingRouter(port=0, probe_interval_s=30.0)  # manual probes
        for rid, (_, srv, health) in enumerate(replicas):
            router.set_endpoint(rid, "127.0.0.1", srv.port,
                                health_url=health.url)
        router.start()
        return router, replicas

    def _teardown(self, router, replicas):
        router.close()
        for _, srv, health in replicas:
            srv.close()
            health.close()

    def test_round_robin_spreads_connections(self):
        router, replicas = self._fleet(2)
        try:
            clients = [ServingClient("127.0.0.1", router.port, seed=i)
                       for i in range(4)]
            for c in clients:
                c.act(np.zeros(8, np.uint8), timeout=10.0)
            served = [srv.accepted for _, srv, _ in replicas]
            assert sum(served) == 4
            assert all(s > 0 for s in served), served
            for c in clients:
                c.close()
        finally:
            self._teardown(router, replicas)

    def test_unhealthy_replica_drains_and_reenters(self):
        router, replicas = self._fleet(2)
        try:
            # Replica 0 goes 503: the probe drains it from rotation.
            replicas[0][2].ok = False
            router.probe_once()
            assert router.stats()["healthy"] == 1
            before = replicas[0][1].accepted
            clients = [ServingClient("127.0.0.1", router.port, seed=i)
                       for i in range(4)]
            for c in clients:
                c.act(np.zeros(8, np.uint8), timeout=10.0)
            # ZERO new connections routed to the drained replica; every
            # request answered by the healthy one (its version on replies).
            assert replicas[0][1].accepted == before
            assert replicas[1][1].stats()["requests"] >= 4
            for c in clients:
                c.close()
            # Recovery: healthz 200 again -> back in rotation.
            replicas[0][2].ok = True
            router.probe_once()
            assert router.stats()["healthy"] == 2
            after = [ServingClient("127.0.0.1", router.port, seed=10 + i)
                     for i in range(4)]
            for c in after:
                c.act(np.zeros(8, np.uint8), timeout=10.0)
            assert replicas[0][1].accepted > before
            for c in after:
                c.close()
        finally:
            self._teardown(router, replicas)

    def test_dead_replica_failover_client_retries(self):
        """SIGKILL-shaped death mid-stream (the in-process twin: close
        the replica's listener and sockets): the client's next request
        rides a reconnect to the LIVE replica — zero drops."""
        router, replicas = self._fleet(2)
        try:
            c = ServingClient("127.0.0.1", router.port, seed=0)
            first = c.act(np.zeros(8, np.uint8), timeout=10.0)
            victim = first.param_version - 1      # rid == version - 1
            live = 1 - victim
            replicas[victim][1].close()           # dies mid-stream
            replicas[victim][2].ok = False
            router.probe_once()
            r = c.act(np.zeros(8, np.uint8), timeout=30.0)
            assert r.param_version == live + 1    # served by the live one
            assert c.reconnects >= 1
            c.close()
        finally:
            self._teardown(router, replicas)

    def test_no_healthy_replicas_fails_fast_then_recovers(self):
        router, replicas = self._fleet(1)
        try:
            replicas[0][2].ok = False
            router.probe_once()
            c = ServingClient("127.0.0.1", router.port, seed=0)
            with pytest.raises(TimeoutError):
                c.act(np.zeros(8, np.uint8), timeout=1.5)
            assert router.stats()["route_fails"] >= 1
            replicas[0][2].ok = True
            router.probe_once()
            assert c.act(np.zeros(8, np.uint8), timeout=10.0) is not None
            c.close()
        finally:
            self._teardown(router, replicas)

    def test_stats_schema_stable(self):
        router = ServingRouter(port=0)
        try:
            keys = set(router.stats())
            assert {"port", "replicas", "healthy", "active",
                    "routed_total", "route_fails", "splices_broken",
                    "probe_failures", "endpoints"} == keys
        finally:
            router.close()


class TestHubSpec:
    def test_parse_roundtrip(self):
        spec = parse_hub_spec("10.0.0.5:9100:12345:3:2")
        assert spec == {"host": "10.0.0.5", "port": 9100, "token": 12345,
                        "wid": 3, "attempt": 2}

    def test_parse_rejects_malformed(self):
        with pytest.raises(ValueError):
            parse_hub_spec("localhost:9100")


class TestSocketParamSource:
    def test_full_then_delta_over_hub(self):
        from ape_x_dqn_tpu.runtime.net import NetTransport
        from ape_x_dqn_tpu.serving.sources import SocketParamSource
        from ape_x_dqn_tpu.utils.serialization import tree_to_bytes

        template = {"w": np.zeros((64, 64), np.float32),
                    "b": np.zeros(64, np.float32)}
        hub = NetTransport(port=0)
        hub.make_channel(0, 0)
        try:
            params1 = {"w": np.ones((64, 64), np.float32),
                       "b": np.zeros(64, np.float32)}
            hub.set_params(tree_to_bytes(params1), 1)
            src = SocketParamSource(
                f"127.0.0.1:{hub.port}:{hub.token}:0:0", template
            )
            got = None
            deadline = time.monotonic() + 10.0
            while got is None and time.monotonic() < deadline:
                hub.pump()
                got = src.get(-1)
                time.sleep(0.01)
            assert got is not None, "no full sync over the hub"
            params, version = got
            assert version == 1
            np.testing.assert_array_equal(params["w"], params1["w"])
            # Delta publish: one small region dirty.
            params2 = {"w": params1["w"].copy(), "b": params1["b"].copy()}
            params2["b"][:] = 3.0
            push = hub.set_params(tree_to_bytes(params2), 2)
            assert push["delta"] == 1
            assert push["bytes"] < len(tree_to_bytes(params2)) / 4
            got = None
            deadline = time.monotonic() + 10.0
            while got is None and time.monotonic() < deadline:
                hub.pump()
                got = src.get(1)
                time.sleep(0.01)
            assert got is not None, "no delta update over the hub"
            params, version = got
            assert version == 2
            np.testing.assert_array_equal(params["b"], params2["b"])
            assert src.version == 2
            src.close()
        finally:
            hub.close()


class TestParamTail:
    def _tree(self, fill):
        return {"w": np.full((128, 32), fill, np.float32),
                "b": np.zeros(32, np.float32)}

    def test_full_then_delta_chain(self, tmp_path):
        w = ParamTailWriter(str(tmp_path), base_every=8)
        src = ParamTailSource(str(tmp_path), self._tree(0.0))
        w.publish(self._tree(1.0))
        params, v = src.get(-1)
        assert v == 1
        np.testing.assert_array_equal(params["w"],
                                      self._tree(1.0)["w"])
        # Small perturbations -> delta files.
        t = self._tree(1.0)
        for i in range(3):
            t["b"][:] = float(i + 1)
            w.publish(t)
        assert w.delta_writes == 3 and w.full_writes == 1
        params, v = src.get(1)
        assert v == 4
        np.testing.assert_array_equal(params["b"], t["b"])
        # Nothing new -> None.
        assert src.get(4) is None

    def test_base_every_forces_full(self, tmp_path):
        w = ParamTailWriter(str(tmp_path), base_every=2)
        t = self._tree(1.0)
        for i in range(4):
            t["b"][:] = float(i)
            w.publish(t)
        assert w.full_writes >= 2

    def test_corrupt_delta_walks_back(self, tmp_path):
        w = ParamTailWriter(str(tmp_path), base_every=16)
        t = self._tree(1.0)
        w.publish(t)
        t["b"][:] = 2.0
        w.publish(t)
        t["b"][:] = 3.0
        path3 = w.publish(t)
        # Bit-flip the newest delta: a FRESH reader must stop the chain
        # at the last good rung (version 2), never decode the bad one.
        with open(path3, "r+b") as f:
            f.seek(40)
            b = f.read(1)
            f.seek(40)
            f.write(bytes([b[0] ^ 0xFF]))
        src = ParamTailSource(str(tmp_path), self._tree(0.0))
        params, v = src.get(-1)
        assert v == 2
        assert src.corrupt_skips >= 1
        np.testing.assert_array_equal(
            params["b"], np.full(32, 2.0, np.float32)
        )

    def test_corrupt_full_uses_previous_generation(self, tmp_path):
        w = ParamTailWriter(str(tmp_path), base_every=2)
        t = self._tree(1.0)
        for i in range(4):          # fulls at v1, v3 (base_every=2)
            t["b"][:] = float(i + 1)
            w.publish(t)
        import os as _os

        newest_full = sorted(
            n for n in _os.listdir(tmp_path) if n.endswith("_full.apxc")
        )[-1]
        with open(tmp_path / newest_full, "r+b") as f:
            f.seek(30)
            f.write(b"\xde\xad")
        src = ParamTailSource(str(tmp_path), self._tree(0.0))
        got = src.get(-1)
        assert got is not None
        _, v = got
        assert v < 4 and src.corrupt_skips >= 1

    def test_pruning_bounds_directory(self, tmp_path):
        w = ParamTailWriter(str(tmp_path), base_every=4)
        t = self._tree(1.0)
        for i in range(20):
            t["b"][:] = float(i)
            w.publish(t)
        names = list(tmp_path.iterdir())
        # Current chain + previous full's chain at most: 2 * base_every.
        assert len(names) <= 2 * 4 + 1
