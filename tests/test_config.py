"""Config system tests: reference-JSON parity load, validation, overrides."""

import json

import pytest

from ape_x_dqn_tpu.config import (
    ApexConfig,
    apply_overrides,
    from_reference_json,
    load_config,
)

REFERENCE_STYLE = {
    "env_conf": {"state_shape": [1, 84, 84], "action_dim": 4,
                 "name": "RiverraidNoFrameskip-v4"},
    "Actor": {"num_actors": 5, "T": 50000, "num_steps": 3, "epsilon": 0.4,
              "alpha": 7, "gamma": 0.99, "n_step_transition_batch_size": 5,
              "Q_network_sync_freq": 500},
    "Learner": {"remove_old_xp_freq": 100, "q_target_sync_freq": 2500,
                "min_replay_mem_size": 20000, "replay_sample_size": 32,
                "load_saved_state": False},
    "Replay_Memory": {"soft_capacity": 100000, "priority_exponent": 0.6,
                      "importance_sampling_exponent": 0.4},
}


def test_reference_json_roundtrip():
    cfg = from_reference_json(REFERENCE_STYLE)
    assert cfg.actor.num_actors == 5
    assert cfg.actor.num_steps == 3
    assert cfg.actor.sync_every == 500
    assert cfg.learner.q_target_sync_freq == 2500
    assert cfg.learner.min_replay_mem_size == 20000
    assert cfg.replay.capacity == 100000
    assert cfg.replay.priority_exponent == 0.6
    assert cfg.replay.is_exponent == 0.4  # dead in the reference, live here
    assert cfg.env.name == "RiverraidNoFrameskip-v4"


def test_unknown_reference_key_rejected():
    bad = {"Actor": {"num_actors": 5, "warp_speed": 9}}
    with pytest.raises(ValueError, match="unknown config key"):
        from_reference_json(bad)


def test_validation_catches_bad_values():
    cfg = ApexConfig()
    cfg.actor.epsilon = 1.5
    with pytest.raises(ValueError, match="epsilon"):
        cfg.validate()
    cfg = ApexConfig()
    cfg.replay.capacity = 8
    cfg.learner.replay_sample_size = 32
    with pytest.raises(ValueError, match="capacity"):
        cfg.validate()
    cfg = ApexConfig()
    cfg.network = "transformer"
    with pytest.raises(ValueError, match="network"):
        cfg.validate()


def test_overrides():
    cfg = apply_overrides(ApexConfig(), ["actor.num_actors=64", "network=mlp",
                                         "learner.learning_rate=0.001"])
    assert cfg.actor.num_actors == 64
    assert cfg.network == "mlp"
    assert cfg.learner.learning_rate == 0.001


def test_override_unknown_path_rejected():
    with pytest.raises(ValueError, match="unknown config"):
        apply_overrides(ApexConfig(), ["actor.bogus=1"])


def test_load_config_file_formats(tmp_path):
    ref = tmp_path / "params.json"
    ref.write_text(json.dumps(REFERENCE_STYLE))
    cfg = load_config(str(ref))
    assert cfg.actor.num_actors == 5

    native = tmp_path / "native.json"
    native.write_text(json.dumps(
        {"actor": {"num_actors": 3}, "network": "mlp", "seed": 42}
    ))
    cfg = load_config(str(native), overrides=["actor.gamma=0.95"])
    assert cfg.actor.num_actors == 3 and cfg.seed == 42
    assert cfg.actor.gamma == 0.95


def test_native_unknown_key_rejected(tmp_path):
    native = tmp_path / "native.json"
    native.write_text(json.dumps({"actor": {"bogus": 1}}))
    with pytest.raises(ValueError, match="unknown config keys"):
        load_config(str(native))
