"""Process-parallel actor tests (VERDICT r2 item 3): shared-memory seqlock,
worker processes feeding a learner, param-version propagation.

These run real OS processes (spawn context, CPU-only jax in workers), so
they are the slowest tests in the suite — kept few and sharp.
"""

import threading
import time

import jax
import numpy as np
import pytest

from ape_x_dqn_tpu.config import ApexConfig
from ape_x_dqn_tpu.runtime.process_actors import (
    ProcessActorPool,
    SharedBufferParamSource,
    SharedMemoryParamStore,
    SharedParamBuffer,
)


class TestSharedParamBuffer:
    def test_write_read_roundtrip(self):
        buf = SharedParamBuffer(1024)
        try:
            assert buf.read(-1, timeout=0.05) is None  # nothing published
            v = buf.write(b"hello")
            assert v == 1
            payload, version = buf.read(-1)
            assert payload == b"hello" and version == 1
            # Same version is filtered by have_version.
            assert buf.read(1, timeout=0.05) is None
            v = buf.write(b"world!")
            payload, version = buf.read(1)
            assert payload == b"world!" and version == 2
        finally:
            buf.close()

    def test_capacity_guard(self):
        buf = SharedParamBuffer(8)
        try:
            with pytest.raises(ValueError, match="exceeds"):
                buf.write(b"123456789")
        finally:
            buf.close()

    def test_torn_write_times_out_not_hangs(self):
        """A writer that died mid-write (odd version) must not hang readers."""
        buf = SharedParamBuffer(64)
        try:
            import struct

            struct.Struct("<qq").pack_into(buf._shm.buf, 0, 1, 4)  # odd
            t0 = time.monotonic()
            assert buf.read(-1, timeout=0.1) is None
            assert time.monotonic() - t0 < 1.0
        finally:
            buf.close()

    def test_concurrent_reader_never_sees_torn_payload(self):
        buf = SharedParamBuffer(4096)
        stop = threading.Event()
        bad = []

        def reader():
            while not stop.is_set():
                got = buf.read(-1, timeout=0.05)
                if got is not None:
                    payload, _ = got
                    if len(set(payload)) != 1:  # must be homogeneous
                        bad.append(payload)

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        try:
            for i in range(200):
                byte = bytes([i % 251])
                buf.write(byte * 2048)
            stop.set()
            t.join(5.0)
            assert not bad, f"torn payloads observed: {len(bad)}"
        finally:
            stop.set()
            buf.close()


class TestStoreAndSource:
    def test_params_roundtrip_via_shared_memory(self):
        from ape_x_dqn_tpu.models.dueling import DuelingMLP

        net = DuelingMLP(num_actions=3, hidden_sizes=(8,))
        params = net.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.float32))
        host = jax.device_get(params)
        buf = SharedParamBuffer(1 << 20)
        try:
            store = SharedMemoryParamStore(buf)
            v = store.publish(host)
            assert v == 1 and store.version == 1
            template = net.init(jax.random.PRNGKey(7), np.zeros((1, 4), np.float32))
            source = SharedBufferParamSource(buf, jax.device_get(template))
            restored, version = source.get(-1)
            assert version == 1
            for a, b in zip(jax.tree_util.tree_leaves(host),
                            jax.tree_util.tree_leaves(restored)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            assert source.get(1) is None  # no new version
        finally:
            buf.close()


class TestEndToEnd:
    def test_two_actor_processes_feed_learner(self):
        """VERDICT r2 'done' criterion: >=2 actor *processes* + learner
        training the chain MDP, with param-version propagation asserted."""
        from ape_x_dqn_tpu.runtime.async_pipeline import AsyncPipeline

        cfg = ApexConfig()
        cfg.network = "mlp"
        cfg.env.name = "chain:6"
        cfg.actor.mode = "process"
        cfg.actor.num_workers = 2
        cfg.actor.num_actors = 4
        cfg.actor.T = 100_000
        cfg.actor.flush_every = 8
        cfg.actor.sync_every = 16
        cfg.learner.min_replay_mem_size = 256
        cfg.learner.publish_every = 5
        cfg.learner.total_steps = 200
        cfg.learner.optimizer = "adam"
        cfg.learner.learning_rate = 1e-3
        cfg.replay.capacity = 4096
        pipe = AsyncPipeline(cfg, log_every=100)
        result = pipe.run(learner_steps=200, warmup_timeout=240.0)
        pool = pipe.worker.pool
        assert result["step"] >= 200
        assert result["actor_steps"] > 0
        # Experience flowed from worker processes.  (Both-workers coverage
        # lives in test_both_workers_deliver_chunks — with the off-thread
        # publisher the learner can finish 200 steps before the slower
        # worker's first chunk lands, so requiring both HERE is a race.)
        assert set(pool.last_versions) <= {0, 1} and pool.last_versions
        # Param-version propagation: chunks arriving late in the run carry a
        # version beyond the initial publish — workers really did re-pull
        # through the shared-memory store.
        assert pipe.store.version > 1
        assert max(pool.last_versions.values()) > 1
        assert not pool.worker_errors
        # Learner actually trained on the workers' experience.
        assert np.isfinite(result.get("learner/loss", 0.0))


class TestBothWorkers:
    def test_both_workers_deliver_chunks(self):
        """Every worker owns a slice of the global actor set and must feed
        experience — polled at pool level (no learner-step race)."""
        from ape_x_dqn_tpu.runtime.process_actors import (
            ProcessActorPool,
            network_and_template,
        )

        cfg = ApexConfig()
        cfg.network = "mlp"
        cfg.env.name = "chain:6"
        cfg.actor.mode = "process"
        cfg.actor.num_workers = 2
        cfg.actor.num_actors = 4
        cfg.actor.T = 100_000
        cfg.actor.flush_every = 8
        cfg.validate()
        pool = ProcessActorPool(cfg, num_workers=2, quantum=8)
        try:
            _, _, template = network_and_template(cfg)
            pool.publish(template)
            pool.start()
            deadline = time.monotonic() + 180.0
            while set(pool.last_versions) != {0, 1} \
                    and time.monotonic() < deadline:
                pool.poll(max_items=64, timeout=0.05)
            assert set(pool.last_versions) == {0, 1}
            assert not pool.worker_errors
        finally:
            pool.stop()


class TestBudgetAccounting:
    def test_worker_lands_on_T_exactly(self):
        """Process-mode twin of the thread fleet's exact-T clamp: a quantum
        that doesn't divide actor.T must not overshoot the budget."""
        from ape_x_dqn_tpu.runtime.process_actors import (
            ProcessActorPool,
            network_and_template,
        )

        cfg = ApexConfig()
        cfg.network = "mlp"
        cfg.env.name = "chain:6"
        cfg.actor.mode = "process"
        cfg.actor.num_workers = 1
        cfg.actor.num_actors = 2
        cfg.actor.T = 53  # 53 % 8 != 0
        cfg.actor.flush_every = 8
        cfg.validate()
        pool = ProcessActorPool(cfg, num_workers=1, quantum=8)
        try:
            _, _, template = network_and_template(cfg)
            pool.publish(template)
            pool.start()
            deadline = time.monotonic() + 120.0
            while not pool.finished and time.monotonic() < deadline:
                pool.poll(max_items=64, timeout=0.05)
            assert pool.finished and not pool.worker_errors
            assert pool.final_steps == {0: 53}
        finally:
            pool.stop()


class TestElasticRecovery:
    def test_sigkilled_worker_respawns_and_feeds_again(self):
        """SURVEY §5 failure detection: a worker killed mid-run (no error
        message — the OOM-kill shape) is respawned by the supervisor with
        its remaining budget and resumes feeding experience."""
        import os
        import signal

        from ape_x_dqn_tpu.runtime.process_actors import (
            ProcessActorPool,
            network_and_template,
        )

        cfg = ApexConfig()
        cfg.network = "mlp"
        cfg.env.name = "chain:6"
        cfg.actor.num_actors = 2
        cfg.actor.T = 1_000_000
        cfg.actor.flush_every = 8
        cfg.actor.sync_every = 32
        pool = ProcessActorPool(cfg, num_workers=2)
        try:
            _, _, params = network_and_template(cfg)
            pool.publish(params)
            pool.start()

            def drain_until(cond, timeout_s):
                deadline = time.monotonic() + timeout_s
                while time.monotonic() < deadline:
                    pool.supervise()
                    pool.poll(max_items=64, timeout=0.1)
                    if cond():
                        return True
                return False

            assert drain_until(lambda: set(pool.last_versions) == {0, 1}, 240)
            victim = pool._procs[0]
            os.kill(victim.pid, signal.SIGKILL)
            victim.join(10.0)
            steps_before = pool._steps_by_worker.get(0, 0)
            # Generous deadlines: worker spawn + jax import takes tens of
            # seconds on a loaded 1-core machine (observed flake in the full
            # suite at 30 s).
            assert drain_until(lambda: pool.restarts >= 1, 120)
            assert not pool.worker_errors  # respawned, not fatal
            # The replacement produces experience again.
            assert drain_until(
                lambda: pool._steps_by_worker.get(0, 0) > steps_before, 240
            )
        finally:
            pool.stop()

    def test_restart_budget_exhaustion_is_fatal(self):
        """After max_restarts deaths, the next one lands in worker_errors
        (the pipeline's stop signal) instead of respawning forever."""
        import os
        import signal

        from ape_x_dqn_tpu.runtime.process_actors import (
            ProcessActorPool,
            network_and_template,
        )

        cfg = ApexConfig()
        cfg.network = "mlp"
        cfg.env.name = "chain:6"
        cfg.actor.num_actors = 2
        cfg.actor.T = 1_000_000
        cfg.actor.flush_every = 8
        pool = ProcessActorPool(cfg, num_workers=2, max_restarts=1)
        try:
            _, _, params = network_and_template(cfg)
            pool.publish(params)
            pool.start()
            deadline = time.monotonic() + 240
            kills = 0
            last_seen = -1  # only kill AFTER new progress since the last
            # kill, so each incarnation demonstrably ran (not killed during
            # its jax-import startup window)
            while time.monotonic() < deadline and not pool.worker_errors:
                pool.supervise()
                pool.poll(max_items=64, timeout=0.1)
                p = pool._procs[0]
                steps = pool._steps_by_worker.get(0, 0)
                if p.is_alive() and steps > last_seen \
                        and 0 in pool.last_versions:
                    last_seen = steps
                    os.kill(p.pid, signal.SIGKILL)
                    p.join(10.0)
                    kills += 1
            assert 0 in pool.worker_errors, (kills, pool.restarts)
            assert pool.restarts == 1
        finally:
            pool.stop()
