"""shm experience-ring tests: SPSC framing, wraparound, backpressure, the
APXT wire-format identity, and the SIGKILL-mid-write salvage discipline
(the shm analogue of round 5's mp.Queue deadlock finding)."""

import os
import signal
import struct
import time

import numpy as np
import pytest

from ape_x_dqn_tpu.runtime.shm_ring import (
    DXP,
    XP,
    ShmRing,
    decode_chunk,
    encode_chunk_parts,
    pack_array_parts,
    unpack_arrays,
)


def _ring_pair(capacity):
    owner = ShmRing(capacity)
    writer = ShmRing(capacity, name=owner.name, create=False)
    return owner, writer


class TestShmRing:
    def test_roundtrip_and_order(self):
        reader, writer = _ring_pair(1 << 12)
        try:
            assert reader.read_next() is None  # fresh ring: no phantom
            for i in range(5):
                assert writer.try_write([bytes([i]) * 100])
            for i in range(5):
                assert reader.read_next() == bytes([i]) * 100
            assert reader.read_next() is None
        finally:
            writer.close()
            reader.close()
            reader.unlink()

    def test_gathered_parts_concatenate(self):
        reader, writer = _ring_pair(1 << 12)
        try:
            arr = np.arange(64, dtype=np.uint8)
            assert writer.try_write([b"head", arr, b"tail"])
            assert reader.read_next() == b"head" + arr.tobytes() + b"tail"
        finally:
            writer.close()
            reader.close()
            reader.unlink()

    def test_wraparound_many_laps(self):
        """Records byte-wrap across the ring end; content survives laps."""
        reader, writer = _ring_pair(1000)  # deliberately unaligned
        try:
            for i in range(200):
                payload = bytes([i % 251]) * (100 + i % 37)
                assert writer.try_write([payload])
                assert reader.read_next() == payload
        finally:
            writer.close()
            reader.close()
            reader.unlink()

    def test_backpressure_and_release(self):
        reader, writer = _ring_pair(2048)
        try:
            n = 0
            while writer.try_write([b"x" * 400]):
                n += 1
            assert 1 <= n <= 5
            assert not writer.try_write([b"x" * 400])
            assert writer.write([b"x" * 400], timeout=0.05) is False
            assert writer.full_waits > 0  # backpressure was counted
            assert reader.read_next() is not None  # free one record
            assert writer.try_write([b"x" * 400])
        finally:
            writer.close()
            reader.close()
            reader.unlink()

    def test_oversized_record_raises(self):
        reader, writer = _ring_pair(1 << 10)
        try:
            with pytest.raises(ValueError, match="xp_ring_bytes"):
                writer.try_write([b"y" * 4096])
        finally:
            writer.close()
            reader.close()
            reader.unlink()

    def test_torn_tail_detected_not_delivered(self):
        """A writer that died between the intent mark and the commit word
        (the SIGKILL-mid-record shape) leaves a tail the reader detects as
        torn and never delivers — while every committed record salvages."""
        reader, writer = _ring_pair(1 << 12)
        try:
            assert writer.try_write([b"committed-record"])
            # Emulate the kill deterministically: intent mark + partial
            # payload, no commit word (exactly the write() store order).
            writer._set(32, writer.started + 1)          # w_started
            writer._copy_in(writer._widx + 16, memoryview(b"half-writ"))
            assert reader.read_next() == b"committed-record"
            assert reader.read_next() is None
            assert reader.torn_tail()
            assert reader.records_read == 1
        finally:
            writer.close()
            reader.close()
            reader.unlink()

    def test_stale_lap_bytes_never_alias(self):
        """After the ring laps, old record headers sit at reusable offsets
        — their seq words are from earlier indices and must never parse as
        future records."""
        reader, writer = _ring_pair(512)
        try:
            for i in range(40):  # many laps over the same bytes
                assert writer.try_write([bytes([i]) * 64])
                assert reader.read_next() == bytes([i]) * 64
            assert reader.read_next() is None
            assert not reader.torn_tail()
        finally:
            writer.close()
            reader.close()
            reader.unlink()


class TestWireFormat:
    def test_pack_matches_tree_to_bytes(self):
        """The jax-free flat-dict serializer is byte-identical to
        utils/serialization.tree_to_bytes — either end may use either."""
        from ape_x_dqn_tpu.utils.serialization import (
            tree_from_bytes,
            tree_to_bytes,
        )

        rng = np.random.default_rng(3)
        arrays = {
            "obs": rng.integers(0, 255, (7, 5, 5, 1), dtype=np.uint8),
            "action": rng.integers(0, 4, (7,)).astype(np.int32),
            "prio": rng.random(7).astype(np.float32),
            "zz_last": np.float32(1.5) * np.ones((), np.float32),
        }
        blob = b"".join(
            bytes(memoryview(p).cast("B")) if not isinstance(p, bytes)
            else p
            for p in pack_array_parts(arrays)
        )
        assert blob == tree_to_bytes(arrays)
        restored = tree_from_bytes(blob)
        for k, v in arrays.items():
            np.testing.assert_array_equal(np.asarray(restored[k]), v)

    def test_unpack_views_are_zero_copy(self):
        arrays = {"a": np.arange(12, dtype=np.int32).reshape(3, 4)}
        blob = b"".join(
            bytes(memoryview(p).cast("B")) if not isinstance(p, bytes)
            else p
            for p in pack_array_parts(arrays)
        )
        out = unpack_arrays(blob)
        np.testing.assert_array_equal(out["a"], arrays["a"])
        assert not out["a"].flags.writeable  # view over the payload bytes
        assert out["a"].base is not None

    def test_chunk_envelope_roundtrip(self):
        arrays = {
            "prio": np.ones(4, np.float32),
            "frames": np.zeros((5, 2, 2, 1), np.uint8),
        }
        parts = encode_chunk_parts(DXP, 42, 4, arrays, source=3,
                                   chunk_seq=17, prev_frames=9,
                                   trace_id=0x5EED)
        payload = b"".join(
            bytes(memoryview(p).cast("B")) if not isinstance(p, bytes)
            else p
            for p in parts
        )
        kind, ver, sent_t, steps, src, cs, pf, tid, back = (
            decode_chunk(payload)
        )
        assert (kind, ver, steps, src, cs, pf) == (DXP, 42, 4, 3, 17, 9)
        assert tid == 0x5EED
        assert sent_t > 0
        for k, v in arrays.items():
            np.testing.assert_array_equal(back[k], v)

    def test_xp_kind_roundtrip_through_ring(self):
        reader, writer = _ring_pair(1 << 16)
        try:
            arrays = {
                "prio": np.full(3, 0.5, np.float32),
                "obs": np.ones((3, 4, 4, 1), np.uint8),
            }
            assert writer.try_write(encode_chunk_parts(XP, 1, 3, arrays))
            kind, ver, _, steps, _, _, _, tid, back = decode_chunk(
                reader.read_next()
            )
            assert tid == 0  # unsampled default
            assert (kind, ver, steps) == (XP, 1, 3)
            np.testing.assert_array_equal(back["obs"], arrays["obs"])
        finally:
            writer.close()
            reader.close()
            reader.unlink()


class TestSigkillMidWrite:
    def test_sigkill_barrage_salvages_all_committed(self):
        """The adversarial kill test: real producer processes SIGKILLed at
        random moments mid-stream.  Every fully-committed record must be
        salvaged in order; a kill that landed mid-record must surface as a
        torn tail, never as delivered garbage.  (Producers are numpy-only
        — tools/xp_transport loads shm_ring.py by file path — so this
        spawns fast despite being a real-process test.)"""
        from tools.xp_transport import run_sigkill_barrage

        out = run_sigkill_barrage(workers=3, rounds=3, rows=32,
                                  obs_shape=(16, 16, 1), ring_bytes=1 << 18)
        assert out["producers_killed"] == 9
        assert out["committed_chunks"] > 0
        assert out["lost_committed_chunks"] == 0, out
        assert out["seq_errors"] == 0, out
        assert out["salvaged_chunks"] >= out["committed_chunks"]

    def test_pool_salvage_gives_respawn_fresh_ring(self):
        """Pool-level discipline without real jax workers: a dead
        incarnation's committed records salvage into poll(), the torn tail
        is counted, and the respawned incarnation's ring is a NEW segment
        (its stream restarts seq-clean)."""
        from ape_x_dqn_tpu.config import ApexConfig
        from ape_x_dqn_tpu.runtime.process_actors import ProcessActorPool

        cfg = ApexConfig()
        cfg.network = "mlp"
        cfg.env.name = "chain:6"
        cfg.actor.mode = "process"
        cfg.actor.num_workers = 1
        cfg.actor.num_actors = 2
        cfg.validate()
        pool = ProcessActorPool(cfg, num_workers=1, ring_bytes=1 << 16)
        try:
            # Stand in for a worker incarnation: write two committed
            # chunks + one torn tail directly into wid 0's ring.
            pool._queues[0] = pool._ctx.Queue(maxsize=4)
            pool._rings[0] = ShmRing(1 << 16)
            old_name = pool._rings[0].name
            w = ShmRing(1 << 16, name=old_name, create=False)
            arrays = {"prio": np.ones(2, np.float32),
                      "obs": np.zeros((2, 3), np.uint8),
                      "action": np.zeros(2, np.int32),
                      "reward": np.zeros(2, np.float32),
                      "discount": np.ones(2, np.float32),
                      "next_obs": np.zeros((2, 3), np.uint8)}
            assert w.try_write(encode_chunk_parts(XP, 5, 2, arrays))
            assert w.try_write(encode_chunk_parts(XP, 6, 2, arrays))
            w._set(32, w.started + 1)  # torn tail: intent, no commit
            w.close()
            pool._salvage_incarnation(0)
            assert len(pool._salvaged) == 2
            stats = pool.transport_stats()
            assert stats["salvaged_records"] == 2
            assert stats["torn_records"] == 1
            # poll() delivers the salvage; accounting advanced.
            items = pool.poll(max_items=8)
            assert len(items) == 2
            assert pool.last_versions[0] == 6
            assert 0 not in pool._rings  # retired; _spawn would make fresh
        finally:
            pool.stop(join_timeout=1.0)


class TestPoolRingSweep:
    def test_poll_round_robins_rings_with_budget(self):
        """The batched sweep drains multiple rings fairly and respects the
        byte drain budget."""
        from ape_x_dqn_tpu.config import ApexConfig
        from ape_x_dqn_tpu.runtime.process_actors import ProcessActorPool

        cfg = ApexConfig()
        cfg.network = "mlp"
        cfg.env.name = "chain:6"
        cfg.actor.mode = "process"
        cfg.actor.num_workers = 2
        cfg.actor.num_actors = 2
        cfg.validate()
        pool = ProcessActorPool(cfg, num_workers=2, ring_bytes=1 << 16)
        writers = []
        try:
            arrays = {"prio": np.ones(1, np.float32),
                      "obs": np.zeros((1, 3), np.uint8),
                      "action": np.zeros(1, np.int32),
                      "reward": np.zeros(1, np.float32),
                      "discount": np.ones(1, np.float32),
                      "next_obs": np.zeros((1, 3), np.uint8)}
            for wid in range(2):
                pool._queues[wid] = pool._ctx.Queue(maxsize=4)
                pool._rings[wid] = ShmRing(1 << 16)
                w = ShmRing(1 << 16, name=pool._rings[wid].name,
                            create=False)
                writers.append(w)
                for _ in range(6):
                    assert w.try_write(
                        encode_chunk_parts(XP, wid + 1, 1, arrays)
                    )
            # Both rings contribute even with a tiny per-poll item cap.
            items = pool.poll(max_items=8)
            assert len(items) == 8
            assert set(pool.last_versions) == {0, 1}
            # Byte budget bounds one sweep; the remainder arrives next poll.
            rest = pool.poll(max_items=64, max_bytes=1)
            assert len(rest) >= 1  # budget admits at least one record
            total = len(items) + len(rest) + len(pool.poll(max_items=64))
            assert total == 12
        finally:
            for w in writers:
                w.close()
            pool.stop(join_timeout=1.0)


class TestDedupWire:
    def test_pool_decodes_dxp_record_to_dedup_chunk(self):
        """The dedup wire through the transport: a DXP record shaped
        exactly like _worker_main's encode (arrays as APXT buffers, the
        int identity fields on the envelope) decodes back to a faithful
        DedupChunk in poll()."""
        from ape_x_dqn_tpu.config import ApexConfig
        from ape_x_dqn_tpu.runtime.process_actors import ProcessActorPool
        from ape_x_dqn_tpu.types import DedupChunk

        cfg = ApexConfig()
        cfg.network = "mlp"
        cfg.env.name = "chain:6"
        cfg.actor.mode = "process"
        cfg.actor.num_workers = 1
        cfg.actor.num_actors = 2
        cfg.validate()
        rng = np.random.default_rng(7)
        chunk = DedupChunk(
            frames=rng.integers(0, 255, (5, 4, 4, 1), dtype=np.uint8),
            obs_ref=np.array([-2, 0, 1], np.int32),
            next_ref=np.array([2, 3, 4], np.int32),
            action=np.array([0, 1, 2], np.int32),
            reward=rng.normal(size=3).astype(np.float32),
            discount=np.full(3, 0.97, np.float32),
            source=11, chunk_seq=4, prev_frames=6,
        )
        prio = np.array([0.5, 1.0, 2.0], np.float32)
        d = chunk._asdict()
        parts = encode_chunk_parts(
            DXP, 9, 3,
            {"prio": prio,
             **{k: np.asarray(d[k])
                for k in ("frames", "obs_ref", "next_ref", "action",
                          "reward", "discount")}},
            source=d["source"], chunk_seq=d["chunk_seq"],
            prev_frames=d["prev_frames"],
        )
        pool = ProcessActorPool(cfg, num_workers=1, ring_bytes=1 << 16)
        try:
            pool._queues[0] = pool._ctx.Queue(maxsize=4)
            pool._rings[0] = ShmRing(1 << 16)
            w = ShmRing(1 << 16, name=pool._rings[0].name, create=False)
            assert w.try_write(parts)
            w.close()
            items = pool.poll(max_items=4)
            assert len(items) == 1
            got_prio, got = items[0]
            np.testing.assert_array_equal(got_prio, prio)
            assert isinstance(got, DedupChunk)
            assert (got.source, got.chunk_seq, got.prev_frames) == (11, 4, 6)
            for f in ("frames", "obs_ref", "next_ref", "action", "reward",
                      "discount"):
                np.testing.assert_array_equal(getattr(got, f), d[f])
            assert pool.last_versions[0] == 9
        finally:
            pool.stop(join_timeout=1.0)


class TestTransportBudget:
    def test_transport_budget_arithmetic(self):
        from ape_x_dqn_tpu.config import ApexConfig, transport_budget

        cfg = ApexConfig()
        cfg.actor.xp_ring_bytes = 1 << 20
        b = transport_budget(cfg, num_workers=256)
        assert b["workers"] == 256
        assert b["shm_segments"] == 257
        assert b["ring_bytes_total"] == 256 << 20

    def test_ring_knob_validation(self):
        from ape_x_dqn_tpu.config import ApexConfig

        cfg = ApexConfig()
        cfg.actor.xp_ring_bytes = 1024
        with pytest.raises(ValueError, match="xp_ring_bytes"):
            cfg.validate()
