"""Device replay + pallas sampling tests (CPU backend: pallas runs the XLA
fallback; the kernel itself is exercised in interpret mode)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ape_x_dqn_tpu.learner.train_step import (
    build_train_step,
    init_train_state,
    make_optimizer,
)
from ape_x_dqn_tpu.models.dueling import DuelingMLP
from ape_x_dqn_tpu.ops.pallas.sampling import (
    _pallas_sample,
    _two_level_sample,
    _xla_sample,
    sample_indices,
)
from ape_x_dqn_tpu.replay.device import (
    build_fused_learn_step,
    device_replay_add,
    device_replay_restamp_last,
    device_replay_sample,
    device_replay_sample_many,
    device_replay_update_priorities,
    init_device_replay,
)
from ape_x_dqn_tpu.types import NStepTransition


def make_chunk(M, obs_shape=(8,), seed=0):
    r = np.random.default_rng(seed)
    return NStepTransition(
        obs=jnp.asarray(r.integers(0, 255, (M, *obs_shape), dtype=np.uint8)),
        action=jnp.asarray(r.integers(0, 3, (M,), dtype=np.int32)),
        reward=jnp.asarray(r.normal(size=(M,)).astype(np.float32)),
        discount=jnp.full((M,), 0.9, jnp.float32),
        next_obs=jnp.asarray(r.integers(0, 255, (M, *obs_shape), dtype=np.uint8)),
    )


class TestPallasSampling:
    def test_interpret_matches_xla(self, rng):
        pri = jnp.asarray(rng.integers(1, 100, 5000).astype(np.float32))
        total = float(pri.sum())
        targets = jnp.asarray(
            np.sort(rng.random(64)).astype(np.float32) * total * 0.999
        )
        a = _xla_sample(pri, targets)
        b = _pallas_sample(pri, targets, interpret=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_interpret_zero_mass_blocks(self):
        # Whole blocks of zeros must be skipped, non-pow2 length padded.
        pri = np.zeros(5000, np.float32)
        pri[4000] = 1.0
        pri[4999] = 3.0
        targets = jnp.asarray([0.5, 1.5, 3.9], jnp.float32)
        out = _pallas_sample(jnp.asarray(pri), targets, interpret=True)
        assert list(np.asarray(out)) == [4000, 4999, 4999]


class TestTwoLevelSampling:
    """The default sampler: radix-√C two-level inverse-CDF (the TPU-native
    sum-tree).  Integer masses make float32 prefix sums exact, so parity
    with the flat-cumsum oracle is bit-exact here."""

    def test_matches_xla_oracle(self, rng):
        pri = jnp.asarray(rng.integers(1, 100, 5000).astype(np.float32))
        total = float(pri.sum())
        targets = jnp.asarray(
            np.sort(rng.random(64)).astype(np.float32) * total * 0.999
        )
        a = _xla_sample(pri, targets)
        b = _two_level_sample(pri, targets, chunk=256)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_non_divisible_length_padded(self, rng):
        pri = jnp.asarray(rng.integers(1, 10, 777).astype(np.float32))
        total = float(pri.sum())
        targets = jnp.asarray((rng.random(32) * total * 0.999).astype(np.float32))
        np.testing.assert_array_equal(
            np.asarray(_xla_sample(pri, targets)),
            np.asarray(_two_level_sample(pri, targets, chunk=64)),
        )

    def test_zero_mass_rows_skipped(self):
        pri = np.zeros(1024, np.float32)
        pri[700] = 1.0
        pri[1023] = 3.0
        targets = jnp.asarray([0.5, 1.5, 3.9], jnp.float32)
        out = _two_level_sample(jnp.asarray(pri), targets, chunk=128)
        assert list(np.asarray(out)) == [700, 1023, 1023]

    def test_default_dispatch_is_two_level(self, rng):
        pri = jnp.asarray(rng.integers(1, 50, 2048).astype(np.float32))
        total = float(pri.sum())
        targets = jnp.asarray((rng.random(16) * total * 0.999).astype(np.float32))
        np.testing.assert_array_equal(
            np.asarray(sample_indices(pri, targets)),
            np.asarray(_two_level_sample(pri, targets)),
        )


class TestDeviceReplay:
    def test_add_rejects_chunk_wider_than_capacity(self):
        st = init_device_replay(8, (8,))
        with pytest.raises(ValueError, match="exceeds replay capacity"):
            device_replay_add(st, make_chunk(9), jnp.ones(9))

    def test_add_ring_semantics(self):
        st = init_device_replay(8, (8,))
        st = device_replay_add(st, make_chunk(6), jnp.ones(6))
        assert int(st.cursor) == 6 and int(st.count) == 6
        st = device_replay_add(st, make_chunk(4, seed=1), jnp.full(4, 2.0))
        assert int(st.cursor) == 2 and int(st.count) == 10
        # Slots 6,7,0,1 hold the new chunk's mass (2^0.6), slot 2 the old.
        mass = np.asarray(st.mass)
        assert mass[6] == pytest.approx(2 ** 0.6, rel=1e-5)
        assert mass[0] == pytest.approx(2 ** 0.6, rel=1e-5)
        assert mass[2] == pytest.approx(1.0, rel=1e-5)

    def test_sample_contents_roundtrip(self):
        st = init_device_replay(64, (8,))
        chunk = make_chunk(32, seed=3)
        st = device_replay_add(st, chunk, jnp.ones(32))
        batch = device_replay_sample(st, jax.random.PRNGKey(0), 16)
        idx = np.asarray(batch.indices)
        assert (idx < 32).all()
        np.testing.assert_array_equal(
            np.asarray(batch.transition.obs), np.asarray(chunk.obs)[idx]
        )
        np.testing.assert_array_equal(
            np.asarray(batch.transition.action), np.asarray(chunk.action)[idx]
        )

    def test_sampling_proportional(self):
        st = init_device_replay(4, (8,))
        st = device_replay_add(
            st, make_chunk(4), jnp.asarray([1.0, 1.0, 1.0, 100.0]),
            priority_exponent=1.0,
        )
        counts = np.zeros(4)
        for k in range(50):
            b = device_replay_sample(st, jax.random.PRNGKey(k), 64)
            counts += np.bincount(np.asarray(b.indices), minlength=4)
        frac = counts[3] / counts.sum()
        assert abs(frac - 100 / 103) < 0.02

    def test_update_priorities_scatter(self):
        st = init_device_replay(8, (8,))
        st = device_replay_add(st, make_chunk(8), jnp.ones(8), priority_exponent=1.0)
        st = device_replay_update_priorities(
            st, jnp.asarray([2, 5]), jnp.asarray([10.0, 20.0]), priority_exponent=1.0
        )
        mass = np.asarray(st.mass)
        assert mass[2] == 10.0 and mass[5] == 20.0 and mass[0] == 1.0

    def test_is_weights_beta_one(self):
        st = init_device_replay(4, (8,))
        st = device_replay_add(
            st, make_chunk(4), jnp.asarray([1.0, 1.0, 2.0, 4.0]),
            priority_exponent=1.0,
        )
        b = device_replay_sample(st, jax.random.PRNGKey(1), 128, beta=1.0)
        w = np.asarray(b.is_weights)
        idx = np.asarray(b.indices)
        if (idx <= 1).any() and (idx == 3).any():
            assert np.allclose(w[idx <= 1], 1.0)
            assert np.allclose(w[idx == 3], 0.25)


class TestFusedLearnStep:
    def test_chunk_in_k_steps_out(self):
        net = DuelingMLP(num_actions=3, hidden_sizes=(16,))
        opt = make_optimizer("adam", learning_rate=1e-3)
        tstate = init_train_state(net, opt, jax.random.PRNGKey(0),
                                  jnp.zeros((1, 8), jnp.uint8))
        rstate = init_device_replay(256, (8,))
        rstate = device_replay_add(rstate, make_chunk(64), jnp.ones(64))
        base = build_train_step(net, opt, jit=False)
        fused = build_fused_learn_step(base, batch_size=16, steps_per_call=4)
        t2, r2, metrics = fused(
            tstate, rstate, make_chunk(32, seed=7), jnp.ones(32),
            0.4, jax.random.PRNGKey(1),
        )
        assert int(t2.step) == 4
        assert int(r2.count) == 96
        assert metrics.loss.shape == (4,)
        assert np.isfinite(np.asarray(metrics.loss)).all()
        # Priorities were restamped: mass no longer all equal.
        mass = np.asarray(r2.mass)[:96]
        assert mass.std() > 0

    def test_hoisted_target_sync_crossing(self):
        """With sync hoisted (sync_in_step=False + target_sync_freq=K·m),
        target params stay fixed until the scan crosses a freq multiple,
        then equal the online params at the call boundary."""
        net = DuelingMLP(num_actions=3, hidden_sizes=(16,))
        opt = make_optimizer("adam", learning_rate=1e-2)
        tstate = init_train_state(net, opt, jax.random.PRNGKey(0),
                                  jnp.zeros((1, 8), jnp.uint8))
        rstate = init_device_replay(128, (8,))
        rstate = device_replay_add(rstate, make_chunk(64), jnp.ones(64))
        base = build_train_step(net, opt, sync_in_step=False, jit=False)
        fused = build_fused_learn_step(
            base, batch_size=16, steps_per_call=4, target_sync_freq=8,
        )
        t0_target = jax.tree_util.tree_leaves(tstate.target_params)[0].copy()
        # Call 1: step 0→4, no multiple of 8 crossed → target unchanged.
        tstate, rstate, _ = fused(tstate, rstate, make_chunk(8, seed=1),
                                  jnp.ones(8), 0.4, jax.random.PRNGKey(1))
        leaf = jax.tree_util.tree_leaves(tstate.target_params)[0]
        np.testing.assert_array_equal(np.asarray(leaf), np.asarray(t0_target))
        # Call 2: step 4→8 crosses 8 → target == online exactly.
        tstate, rstate, _ = fused(tstate, rstate, make_chunk(8, seed=2),
                                  jnp.ones(8), 0.4, jax.random.PRNGKey(2))
        for on, tg in zip(
            jax.tree_util.tree_leaves(tstate.params),
            jax.tree_util.tree_leaves(tstate.target_params),
        ):
            np.testing.assert_array_equal(np.asarray(on), np.asarray(tg))

    def test_include_ingest_false_signature(self):
        net = DuelingMLP(num_actions=3, hidden_sizes=(16,))
        opt = make_optimizer("adam", learning_rate=1e-3)
        tstate = init_train_state(net, opt, jax.random.PRNGKey(0),
                                  jnp.zeros((1, 8), jnp.uint8))
        rstate = init_device_replay(128, (8,))
        rstate = device_replay_add(rstate, make_chunk(64), jnp.ones(64))
        base = build_train_step(net, opt, sync_in_step=False, jit=False)
        fused = build_fused_learn_step(
            base, batch_size=16, steps_per_call=3, include_ingest=False,
        )
        t2, r2, metrics = fused(tstate, rstate, 0.4, jax.random.PRNGKey(1))
        assert int(t2.step) == 3
        assert int(r2.count) == 64  # no ingest happened
        assert metrics.loss.shape == (3,)

    def test_bf16_knobs_still_learn(self):
        """The HBM-traffic knobs (bf16 second moment, bf16 target) must not
        break optimization: constant-target regression loss still falls."""
        net = DuelingMLP(num_actions=3, hidden_sizes=(32,))
        opt = make_optimizer(
            "rmsprop", learning_rate=3e-3, max_grad_norm=None,
            second_moment_dtype=jnp.bfloat16,
        )
        tstate = init_train_state(
            net, opt, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.uint8),
            target_dtype=jnp.bfloat16,
        )
        tgt_leaf = jax.tree_util.tree_leaves(tstate.target_params)[0]
        assert tgt_leaf.dtype == jnp.bfloat16
        rstate = init_device_replay(512, (8,))
        base = build_train_step(net, opt, sync_in_step=False, jit=False)
        fused = build_fused_learn_step(base, batch_size=32, steps_per_call=8,
                                       target_sync_freq=64)
        r = np.random.default_rng(0)
        losses = []
        for it in range(12):
            chunk = NStepTransition(
                obs=jnp.asarray(r.integers(0, 255, (32, 8), dtype=np.uint8)),
                action=jnp.asarray(r.integers(0, 3, (32,), dtype=np.int32)),
                reward=jnp.ones((32,), jnp.float32),
                discount=jnp.zeros((32,), jnp.float32),
                next_obs=jnp.asarray(r.integers(0, 255, (32, 8), dtype=np.uint8)),
            )
            tstate, rstate, metrics = fused(
                tstate, rstate, chunk, jnp.ones(32), 0.4, jax.random.PRNGKey(it)
            )
            losses.append(float(np.asarray(metrics.loss)[-1]))
        assert losses[-1] < losses[0] * 0.5, losses

    def test_fused_loop_learns(self):
        """Constant-target regression through the fused path: loss falls."""
        net = DuelingMLP(num_actions=3, hidden_sizes=(32,))
        opt = make_optimizer("adam", learning_rate=3e-3)
        tstate = init_train_state(net, opt, jax.random.PRNGKey(0),
                                  jnp.zeros((1, 8), jnp.uint8))
        rstate = init_device_replay(512, (8,))
        base = build_train_step(net, opt, jit=False)
        fused = build_fused_learn_step(base, batch_size=32, steps_per_call=8)
        r = np.random.default_rng(0)
        losses = []
        for it in range(12):
            chunk = NStepTransition(
                obs=jnp.asarray(r.integers(0, 255, (32, 8), dtype=np.uint8)),
                action=jnp.asarray(r.integers(0, 3, (32,), dtype=np.int32)),
                reward=jnp.ones((32,), jnp.float32),
                discount=jnp.zeros((32,), jnp.float32),
                next_obs=jnp.asarray(r.integers(0, 255, (32, 8), dtype=np.uint8)),
            )
            tstate, rstate, metrics = fused(
                tstate, rstate, chunk, jnp.ones(32), 0.4, jax.random.PRNGKey(it)
            )
            losses.append(float(np.asarray(metrics.loss)[-1]))
        assert losses[-1] < losses[0] * 0.5, losses


class TestSampleAhead:
    """The batched sample-ahead spellings (device_replay_sample_many /
    device_replay_restamp_last) behind ``sample_ahead=True``."""

    def test_sample_many_shapes_and_contents(self):
        st = init_device_replay(64, (8,))
        chunk = make_chunk(48, seed=3)
        st = device_replay_add(st, chunk, jnp.ones(48))
        b = device_replay_sample_many(st, jax.random.PRNGKey(0), 5, 16)
        assert b.indices.shape == (5, 16)
        assert b.transition.obs.shape == (5, 16, 8)
        assert b.is_weights.shape == (5, 16)
        idx = np.asarray(b.indices)
        assert (idx < 48).all()
        np.testing.assert_array_equal(
            np.asarray(b.transition.obs), np.asarray(chunk.obs)[idx]
        )
        # IS weights max-normalized per batch, not across the K axis.
        w = np.asarray(b.is_weights)
        np.testing.assert_allclose(w.max(axis=1), 1.0, rtol=1e-6)

    def test_sample_many_proportional(self):
        st = init_device_replay(4, (8,))
        st = device_replay_add(
            st, make_chunk(4), jnp.asarray([1.0, 1.0, 1.0, 100.0]),
            priority_exponent=1.0,
        )
        counts = np.zeros(4)
        for k in range(10):
            b = device_replay_sample_many(st, jax.random.PRNGKey(k), 8, 64)
            counts += np.bincount(np.asarray(b.indices).ravel(), minlength=4)
        frac = counts[3] / counts.sum()
        assert abs(frac - 100 / 103) < 0.02

    def test_restamp_last_wins_matches_sequential(self):
        """Batched restamp == K sequential scatters (last write wins)."""
        st = init_device_replay(16, (8,))
        st = device_replay_add(st, make_chunk(16), jnp.ones(16),
                               priority_exponent=1.0)
        r = np.random.default_rng(0)
        K, B = 6, 8
        indices = r.integers(0, 16, (K, B)).astype(np.int32)  # heavy dupes
        prios = r.random((K, B)).astype(np.float32) + 0.1
        seq = st
        for k in range(K):
            seq = device_replay_update_priorities(
                seq, jnp.asarray(indices[k]), jnp.asarray(prios[k]),
                priority_exponent=1.0,
            )
        batched = device_replay_restamp_last(
            st, jnp.asarray(indices), jnp.asarray(prios), priority_exponent=1.0
        )
        np.testing.assert_allclose(
            np.asarray(batched.mass), np.asarray(seq.mass), rtol=1e-6
        )

    def test_sample_ahead_fused_learns(self):
        """Constant-target regression through sample_ahead=True: loss falls
        and priorities were restamped."""
        net = DuelingMLP(num_actions=3, hidden_sizes=(32,))
        opt = make_optimizer("adam", learning_rate=3e-3)
        tstate = init_train_state(net, opt, jax.random.PRNGKey(0),
                                  jnp.zeros((1, 8), jnp.uint8))
        rstate = init_device_replay(512, (8,))
        base = build_train_step(net, opt, sync_in_step=False, jit=False)
        fused = build_fused_learn_step(
            base, batch_size=32, steps_per_call=8, target_sync_freq=64,
            sample_ahead=True,
        )
        r = np.random.default_rng(0)
        losses = []
        for it in range(12):
            chunk = NStepTransition(
                obs=jnp.asarray(r.integers(0, 255, (32, 8), dtype=np.uint8)),
                action=jnp.asarray(r.integers(0, 3, (32,), dtype=np.int32)),
                reward=jnp.ones((32,), jnp.float32),
                discount=jnp.zeros((32,), jnp.float32),
                next_obs=jnp.asarray(r.integers(0, 255, (32, 8), dtype=np.uint8)),
            )
            tstate, rstate, metrics = fused(
                tstate, rstate, chunk, jnp.ones(32), 0.4, jax.random.PRNGKey(it)
            )
            losses.append(float(np.asarray(metrics.loss)[-1]))
        assert int(tstate.step) == 96
        assert losses[-1] < losses[0] * 0.5, losses
        mass = np.asarray(rstate.mass)[:384]
        assert mass.std() > 0  # restamp happened
