"""Observability layer tests (ISSUE 4): registry/exporter/health units,
shm stats blocks under SIGKILL, flight recorder + post-mortems, lineage
tracking, the METRICS.md schema contract, and the process-actor
end-to-end pins (trace-ID'd spans with monotone timestamps; SIGKILL →
salvaged stats block → post-mortem file — same spirit as
tests/test_shm_ring.py)."""

import io
import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import urllib.request

import numpy as np
import pytest

from ape_x_dqn_tpu.obs import (
    FlightRecorder,
    Health,
    LineageTracker,
    MetricsRegistry,
    ObsServer,
    WORKER_SLOTS,
    WorkerStatsBlock,
    write_postmortem,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestMetricsRegistry:
    def test_typed_instruments_get_or_create_and_conflict(self):
        r = MetricsRegistry()
        c = r.counter("chunks")
        assert r.counter("chunks") is c
        c.inc(2)
        assert c.value == 2.0
        with pytest.raises(ValueError, match="already registered"):
            r.gauge("chunks")
        with pytest.raises(ValueError, match="only go up"):
            c.inc(-1)

    def test_gauge_fn_and_histogram(self):
        r = MetricsRegistry()
        r.gauge("step").set_fn(lambda: 7)
        h = r.histogram("lat")
        h.observe(0.01)
        snap = r.snapshot()
        assert snap["step"] == 7.0
        assert snap["lat"]["count"] == 1
        assert snap["lat"]["buckets"]

    def test_provider_failure_degrades_to_error_entry(self):
        r = MetricsRegistry()
        r.register_provider("bad", lambda: 1 / 0)
        r.register_provider("good", lambda: {"x": 1})
        snap = r.snapshot()
        assert "ZeroDivisionError" in snap["bad"]["error"]
        assert snap["good"] == {"x": 1}

    def test_prometheus_text_covers_all_kinds(self):
        r = MetricsRegistry(prefix="apex")
        r.counter("served").inc(5)
        r.gauge("depth").set(3)
        r.histogram("lat").observe(0.02)
        r.register_provider("xp", lambda: {"mb_s": 1.5, "w": {"0": 2}})
        text = r.prometheus_text()
        assert "apex_served_total 5" in text
        assert "apex_depth 3" in text
        assert 'apex_lat{quantile="0.99"}' in text
        assert "apex_xp_mb_s 1.5" in text
        assert "apex_xp_w_0 2" in text
        # Names are sanitized — no slashes survive.
        r.gauge("learner/loss").set(1)
        assert "apex_learner_loss 1" in r.prometheus_text()


class TestHealth:
    def test_beat_then_stale(self):
        h = Health(stale_after_s=0.05)
        h.beat("learner")
        assert h.status()["status"] == "ok"
        time.sleep(0.08)
        st = h.status()
        assert st["status"] == "degraded"
        assert not st["components"]["learner"]["ok"]

    def test_age_fn_and_failure_is_degraded(self):
        h = Health(stale_after_s=1.0)
        h.register("pump", lambda: 0.1)
        h.register("dead", lambda: 1 / 0)
        st = h.status()
        assert st["components"]["pump"]["ok"]
        assert not st["components"]["dead"]["ok"]
        assert st["status"] == "degraded"


class TestObsServer:
    def test_endpoints_and_trace_hook(self):
        r = MetricsRegistry()
        r.gauge("step").set(9)
        h = Health(stale_after_s=60.0)
        h.beat("learner")
        calls = []

        def hook(steps=None):
            calls.append(steps)
            return {"state": "capturing", "steps": steps}

        srv = ObsServer(r, h, port=0, trace_hook=hook)
        try:
            base = srv.url
            txt = urllib.request.urlopen(f"{base}/metrics").read().decode()
            assert "apex_step 9" in txt
            varz = json.load(urllib.request.urlopen(f"{base}/varz"))
            assert varz["step"] == 9.0
            hz = urllib.request.urlopen(f"{base}/healthz")
            assert hz.status == 200
            assert json.load(hz)["status"] == "ok"
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{base}/nope")
            assert ei.value.code == 404
            varz = json.load(
                urllib.request.urlopen(f"{base}/varz?trace=1&steps=32")
            )
            assert varz["trace"]["state"] == "capturing"
            assert calls == [32]
        finally:
            srv.close()

    def test_healthz_503_when_degraded(self):
        h = Health(stale_after_s=0.01)
        h.beat("learner")
        time.sleep(0.03)
        srv = ObsServer(MetricsRegistry(), h, port=0)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{srv.url}/healthz")
            assert ei.value.code == 503
            assert json.load(ei.value)["status"] == "degraded"
        finally:
            srv.close()


class TestWorkerStatsBlock:
    def test_slot_and_event_roundtrip_with_wrap(self):
        b = WorkerStatsBlock(slots=WORKER_SLOTS, event_depth=4)
        try:
            w = WorkerStatsBlock(name=b.name, create=False)
            w.update(env_steps=128, eps_mean=0.25)
            for i in range(7):
                w.record_event({"kind": "collect", "i": i})
            snap = b.snapshot()
            assert snap["env_steps"] == 128.0
            assert snap["eps_mean"] == 0.25
            assert snap["pid"] == os.getpid()
            assert snap["heartbeat_age_s"] < 5.0
            events, torn = b.recent_events()
            # Depth 4: only the newest 4 survive the wrap, in order.
            assert [e["i"] for e in events] == [3, 4, 5, 6]
            assert torn == 0
            w.close()
        finally:
            b.close()
            b.unlink()

    def test_torn_event_slot_is_counted_not_delivered(self):
        b = WorkerStatsBlock(slots=("x",), event_depth=2)
        try:
            w = WorkerStatsBlock(name=b.name, create=False)
            w.record_event({"kind": "good"})
            w.record_event({"kind": "mangled"})
            # Corrupt the newest slot's length word — the SIGKILL-mid-write
            # shape (payload bytes without a coherent frame).
            import struct

            off = b._events_off + (1 % 2) * 256
            struct.pack_into("<I", b._shm.buf, off, 3)  # truncates the JSON
            events, torn = b.recent_events()
            assert [e["kind"] for e in events] == ["good"]
            assert torn == 1
            w.close()
        finally:
            b.close()
            b.unlink()

    def test_sigkilled_writer_leaves_readable_block(self):
        """The core SIGKILL property: a real writer process killed
        mid-stream leaves final slot values + events the parent reads
        afterwards.  The child is stdlib-only (no jax) so this is fast."""
        b = WorkerStatsBlock(slots=WORKER_SLOTS, event_depth=32)
        child = subprocess.Popen(
            [sys.executable, "-c", f"""
import sys, time
sys.path.insert(0, {REPO!r})
from ape_x_dqn_tpu.obs.shm_stats import WorkerStatsBlock
w = WorkerStatsBlock(name={b.name!r}, create=False)
i = 0
while True:
    i += 1
    w.update(env_steps=i, chunks=i * 2)
    w.record_event({{"kind": "tick", "i": i}})
    time.sleep(0.002)
"""],
        )
        try:
            deadline = time.monotonic() + 30.0
            while b.snapshot()["env_steps"] < 10 \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            os.kill(child.pid, signal.SIGKILL)
            child.wait(timeout=10.0)
            snap = b.snapshot()
            assert snap["env_steps"] >= 10
            assert snap["chunks"] == 2 * snap["env_steps"]
            events, torn = b.recent_events()
            assert events, "no events salvaged after SIGKILL"
            assert events[-1]["i"] == int(snap["events_written"])
            assert torn <= 1  # at most the one slot the kill interrupted
        finally:
            if child.poll() is None:
                child.kill()
            b.close()
            b.unlink()


class TestFlightRecorder:
    def test_record_bounds_and_dump_is_atomic_json(self, tmp_path):
        rec = FlightRecorder("trainer", depth=3)
        rec.add_snapshot_provider("state", lambda: {"x": 1})
        rec.add_snapshot_provider("bad", lambda: 1 / 0)
        for i in range(5):
            rec.record("tick", i=i)
        assert [e["i"] for e in rec.events()] == [2, 3, 4]
        path = rec.dump(str(tmp_path), "fault", extra={"why": "test"})
        assert path and os.path.exists(path)
        assert not any(
            f.endswith(".tmp") for f in os.listdir(tmp_path)
        )
        with open(path) as f:
            data = json.load(f)
        assert data["reason"] == "fault"
        assert data["snapshots"]["state"] == {"x": 1}
        assert "ZeroDivisionError" in data["snapshots"]["bad"]["error"]
        assert [e["i"] for e in data["events"]] == [2, 3, 4]

    def test_dump_disabled_and_never_raises(self):
        rec = FlightRecorder()
        assert rec.dump("", "fault") is None
        assert rec.dump("/proc/definitely/not/writable", "fault") is None

    def test_sigterm_install_refused_off_main_thread(self, tmp_path):
        rec = FlightRecorder()
        out = []
        t = threading.Thread(
            target=lambda: out.append(rec.install_sigterm(str(tmp_path)))
        )
        t.start()
        t.join()
        assert out == [False]

    def test_sigterm_flushes_in_a_real_process(self, tmp_path):
        """SIGTERM a process with the handler installed → a post-mortem
        file lands before death (the trainer's graceful-kill path)."""
        child = subprocess.Popen(
            [sys.executable, "-c", f"""
import sys, time
sys.path.insert(0, {REPO!r})
from ape_x_dqn_tpu.obs.recorder import FlightRecorder
r = FlightRecorder("t")
r.record("alive")
assert r.install_sigterm({str(tmp_path)!r})
print("ready", flush=True)
time.sleep(60)
"""],
            stdout=subprocess.PIPE,
        )
        try:
            assert child.stdout.readline().strip() == b"ready"
            child.terminate()
            rc = child.wait(timeout=15.0)
            assert rc != 0  # died of the chained SIGTERM, after the dump
            files = [f for f in os.listdir(tmp_path)
                     if "sigterm" in f and f.endswith(".json")]
            assert files, "no sigterm post-mortem written"
            with open(os.path.join(tmp_path, files[0])) as f:
                assert json.load(f)["events"][0]["kind"] == "alive"
        finally:
            if child.poll() is None:
                child.kill()

    def test_write_postmortem_helper(self, tmp_path):
        path = write_postmortem(str(tmp_path), "worker3", "salvage",
                                {"stats": {"env_steps": 9}})
        with open(path) as f:
            data = json.load(f)
        assert data["name"] == "worker3"
        assert data["stats"]["env_steps"] == 9


class TestLineageTracker:
    def test_full_span_monotone_and_emitted(self):
        events = []
        tr = LineageTracker(
            64, emit=lambda name, **kw: events.append((name, kw))
        )
        idx = np.arange(8)
        tr.on_ingest(idx, t_act=time.monotonic() - 0.01, trace_id=123,
                     wid=2)
        tr.on_sample(idx[:4])
        tr.on_trained(idx[:4])
        assert tr.completed_count == 1
        name, span = events[0]
        assert name == "lineage_span"
        assert span["trace_id"] == 123 and span["wid"] == 2
        ts = [span[k] for k in
              ("t_act", "t_ingest", "t_first_sample", "t_trained")]
        assert ts == sorted(ts)
        assert span["act_to_trained_ms"] >= span["act_to_ingest_ms"]
        # Slots are released — a later sample of them is not traced.
        tr.on_sample(idx)
        assert tr.completed_count == 1

    def test_age_histogram_counts_untraced_samples(self):
        tr = LineageTracker(32)
        tr.on_ingest(np.arange(16))          # trace_id 0: age-only
        tr.on_sample(np.arange(8))
        assert tr.age_hist.count == 8
        s = tr.summary()
        assert s["age_at_sample"]["count"] == 8
        assert s["traces_open"] == 0

    def test_recycled_slot_abandons_open_trace(self):
        tr = LineageTracker(8)
        tr.on_ingest(np.arange(8), trace_id=7)
        tr.on_ingest(np.arange(4))           # ring lapped half the slots
        assert tr.abandoned_count == 1
        assert tr.summary()["traces_open"] == 0


def _doc_keys(section_header):
    # One shared parser now lives with the analyzer (apexlint satellite):
    # the standalone dict-vs-doc pins moved to tests/test_lint.py
    # TestDocSchemaDicts; the pins below need this module's run fixtures.
    from ape_x_dqn_tpu.analysis.metrics_doc import doc_section_keys

    return doc_section_keys(
        section_header, os.path.join(REPO, "docs", "METRICS.md"))


class TestMetricsDocSchema:
    """docs/METRICS.md is a contract: the stamped-keys list and the
    periodic core-key list must match real emitted records exactly.
    (Thin pin retained here — the fixture-free schema-dict pins and the
    static metrics-doc checker live in tests/test_lint.py.)"""

    def test_stamp_keys_match_doc(self):
        from ape_x_dqn_tpu.utils.metrics import emit_event

        doc = _doc_keys("## Stamped on every record")
        assert doc == ["seq", "pid"]
        rec = emit_event("x", stream=io.StringIO())
        assert set(doc) <= set(rec)

    def test_periodic_core_keys_match_doc(self, tiny_thread_run):
        doc = set(_doc_keys("## Periodic record core keys"))
        assert doc, "doc section missing"
        record = tiny_thread_run["final_record"]
        missing = doc - set(record)
        assert not missing, f"documented keys absent from emit: {missing}"
        # And the stamps ride periodic records too.
        assert {"seq", "pid"} <= set(record)

    def test_supervisor_section_matches_doc(self, tiny_thread_run):
        """The supervisor schema rows (ISSUE 6 satellite): the documented
        key list IS the emitted section, and the counters are live on the
        registry (/varz + /metrics surfaces)."""
        doc = _doc_keys("## Supervisor schema")
        assert doc, "Supervisor schema doc section missing"
        record = tiny_thread_run["final_record"]
        assert "supervisor" in record, "supervisor section absent from emit"
        assert set(doc) == set(record["supervisor"]), (
            set(doc) ^ set(record["supervisor"])
        )
        pipe = tiny_thread_run["pipe"]
        snap = pipe.obs_registry.snapshot()
        for name in ("supervisor/respawns", "supervisor/quarantines",
                     "supervisor/degradations",
                     "supervisor/fallback_restores"):
            assert name in snap, name
        assert "apex_supervisor_respawns_total" \
            in pipe.obs_registry.prometheus_text()

    # test_replay_tier/net/serving_net/serving_router_section_matches_doc
    # moved to tests/test_lint.py::TestDocSchemaDicts (apexlint absorbs
    # the fixture-free doc pins; same parser, same assertions).


@pytest.fixture(scope="module")
def tiny_thread_run():
    """One small thread-mode pipeline run shared by the schema + lineage
    tests (chain MDP, mlp — seconds, not minutes)."""
    from ape_x_dqn_tpu.config import ApexConfig
    from ape_x_dqn_tpu.runtime.async_pipeline import AsyncPipeline
    from ape_x_dqn_tpu.utils.metrics import MetricLogger

    cfg = ApexConfig()
    cfg.network = "mlp"
    cfg.env.name = "chain:6"
    cfg.actor.num_actors = 4
    cfg.actor.T = 100_000
    cfg.actor.flush_every = 8
    cfg.actor.sync_every = 16
    cfg.learner.min_replay_mem_size = 256
    cfg.learner.publish_every = 5
    cfg.learner.total_steps = 80
    cfg.learner.optimizer = "adam"
    cfg.learner.learning_rate = 1e-3
    cfg.replay.capacity = 4096
    cfg.obs.trace_sample_rate = 1.0
    cfg.validate()
    buf = io.StringIO()
    pipe = AsyncPipeline(cfg, logger=MetricLogger(stream=buf), log_every=40)
    final = pipe.run(learner_steps=80, warmup_timeout=120.0)
    lines = [json.loads(line) for line in buf.getvalue().splitlines()]
    return {"final_record": final, "lines": lines, "pipe": pipe}


class TestThreadModeLineage:
    def test_spans_complete_and_ride_the_stream(self, tiny_thread_run):
        lines = tiny_thread_run["lines"]
        spans = [r for r in lines if r.get("event") == "lineage_span"]
        assert spans, "no lineage_span events on the JSONL stream"
        for s in spans[:5]:
            ts = [s[k] for k in
                  ("t_act", "t_ingest", "t_first_sample", "t_trained")]
            assert ts == sorted(ts)
        assert all("seq" in r and "pid" in r for r in lines)
        assert tiny_thread_run["final_record"].get("lineage", {}).get(
            "age_at_sample", {}
        ).get("count", 0) > 0


class TestProcessModeObsEndToEnd:
    def test_traced_process_chunk_spans_and_sigkill_postmortem(
        self, tmp_path
    ):
        """The two ISSUE acceptance pins in one fleet run: (a) a trace-ID'd
        chunk from a REAL worker process is observed at ingest, sample,
        and train with monotone spans on the JSONL stream; (b) a SIGKILLed
        worker's shm stats block is salvaged into a post-mortem file.
        (Also exercised CI-side by tools/obs_smoke.py, which verify_t1.sh
        runs on every gate pass — this is the in-suite pin.)"""
        from ape_x_dqn_tpu.config import ApexConfig
        from ape_x_dqn_tpu.runtime.async_pipeline import AsyncPipeline
        from ape_x_dqn_tpu.utils.metrics import MetricLogger

        cfg = ApexConfig()
        cfg.network = "mlp"
        cfg.env.name = "chain:6"
        cfg.actor.mode = "process"
        cfg.actor.num_workers = 1  # one spawn: the costly part of the test
        cfg.actor.num_actors = 2
        cfg.actor.T = 10_000_000
        cfg.actor.flush_every = 8
        cfg.actor.sync_every = 32
        cfg.learner.min_replay_mem_size = 256
        cfg.learner.publish_every = 10
        cfg.learner.total_steps = 10**9
        cfg.learner.optimizer = "adam"
        cfg.replay.capacity = 8192
        cfg.obs.trace_sample_rate = 1.0
        cfg.obs.postmortem_dir = str(tmp_path / "postmortem")
        cfg.validate()
        buf = io.StringIO()
        pipe = AsyncPipeline(
            cfg, logger=MetricLogger(stream=buf), log_every=100
        )
        err = []

        def run():
            try:
                pipe.run(warmup_timeout=300.0)
            except Exception as e:  # noqa: BLE001
                err.append(e)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        try:
            deadline = time.monotonic() + 420.0
            # (a) spans complete from real process-actor chunks.
            while pipe._lineage.completed_count == 0 \
                    and time.monotonic() < deadline:
                assert not err, err
                time.sleep(0.2)
            assert pipe._lineage.completed_count > 0, "no spans completed"
            # (b) SIGKILL one worker → salvage → post-mortem file.
            pool = pipe.worker.pool
            os.kill(pool._procs[0].pid, signal.SIGKILL)
            pm_dir = cfg.obs.postmortem_dir
            while time.monotonic() < deadline:
                if os.path.isdir(pm_dir) and any(
                    f.endswith(".json") for f in os.listdir(pm_dir)
                ):
                    break
                time.sleep(0.2)
            files = [f for f in os.listdir(pm_dir) if f.endswith(".json")]
            assert files, "no post-mortem after SIGKILL"
            with open(os.path.join(pm_dir, files[0])) as f:
                pm = json.load(f)
            assert pm["reason"] == "salvage"
            assert pm["stats"]["env_steps"] > 0
            assert pm["events"], "flight-recorder events not salvaged"
        finally:
            pipe.stop_event.set()
            t.join(timeout=120.0)
        assert not err, err
        spans = [
            json.loads(line) for line in buf.getvalue().splitlines()
            if '"lineage_span"' in line
        ]
        assert spans
        s = spans[0]
        assert s["wid"] is not None  # produced by a real worker process
        ts = [s[k] for k in
              ("t_act", "t_ingest", "t_first_sample", "t_trained")]
        assert ts == sorted(ts)
        # act→ingest crossed a process boundary: strictly positive.
        assert s["t_ingest"] > s["t_act"]
