"""apexlint — the static-analysis suite that enforces the fleet's invariants.

Three layers of coverage:

  * **fixture tests** — each checker pointed at a tiny known-bad tree
    under tests/fixtures/lint/, asserting it fires with the right
    checker id and file:line (and does NOT fire on the blessed idioms);
  * **the repo itself** — the committed tree must lint clean against
    the committed baseline (the pytest twin of verify gate 12), and the
    import-light contract is re-proven DYNAMICALLY by importing each
    contracted module in a subprocess and asserting jax never loads;
  * **doc-schema pins** — the cheap runtime dict-vs-docs/METRICS.md
    comparisons absorbed from test_obs.py (the analyzer's
    ``doc_section_keys`` is now the one shared parser; the pins that
    need a full training run stay with their fixtures in test_obs.py /
    test_central_inference.py / test_replay_svc.py).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from ape_x_dqn_tpu import analysis
from ape_x_dqn_tpu.analysis import (
    config_coverage,
    import_light,
    metrics_doc,
    shm_discipline,
    typed_errors,
    wire_registry,
)
from ape_x_dqn_tpu.analysis.core import IMPORT_LIGHT_CONTRACT, Repo

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "lint")


def _by_key(findings):
    return {f.key: f for f in findings}


# ---------------------------------------------------------------------------
# Checker fixture tests: known-bad trees, exact ids and lines.
# ---------------------------------------------------------------------------


class TestImportLightChecker:
    def test_transitive_smuggle_found_with_chain(self):
        repo = Repo(os.path.join(FIXTURES, "import_light"),
                    rel_dirs=("fixpkg",))
        found = import_light.check(repo, roots=("fixpkg.entry",))
        assert len(found) == 1
        f = found[0]
        assert f.checker == "import-light"
        assert f.path == "fixpkg/middle.py" and f.line == 3
        assert f.key == "fixpkg.entry->jax"
        assert "fixpkg.entry -> fixpkg.middle" in f.message

    def test_function_scope_import_is_legal(self):
        repo = Repo(os.path.join(FIXTURES, "import_light"),
                    rel_dirs=("fixpkg",))
        assert import_light.check(repo, roots=("fixpkg.lazy_ok",)) == []

    def test_missing_contract_root_is_a_finding(self):
        repo = Repo(os.path.join(FIXTURES, "import_light"),
                    rel_dirs=("fixpkg",))
        found = import_light.check(repo, roots=("fixpkg.nonexistent",))
        assert [f.key for f in found] == ["missing-root:fixpkg.nonexistent"]


class TestWireRegistryChecker:
    @pytest.fixture()
    def found(self):
        repo = Repo(os.path.join(FIXTURES, "wire"), rel_dirs=("wirepkg",))
        return _by_key(wire_registry.check(
            repo, net_path="wirepkg/net.py", allowed_dupes={},
            wire_plane=()))

    def test_duplicate_kind_value(self, found):
        f = found["dup-kind-value:F_B"]
        assert f.path == "wirepkg/net.py" and f.line == 4

    def test_dead_kind(self, found):
        f = found["dead-kind:F_C"]
        assert f.path == "wirepkg/net.py" and f.line == 5
        # F_B is both a duplicate value and unreferenced — dead too.
        assert "dead-kind:F_B" in found

    def test_redeclared_kind_outside_registry(self, found):
        f = found["redeclared-kind:wirepkg/consumer.py:F_D"]
        assert f.path == "wirepkg/consumer.py" and f.line == 3

    def test_duplicate_magic(self, found):
        f = found["dup-magic:wirepkg/consumer.py:MAGIC_TWO"]
        assert f.path == "wirepkg/consumer.py" and f.line == 4
        assert "MAGIC_ONE" in f.message

    def test_kind_literal_compare(self, found):
        f = found["kind-literal:wirepkg/consumer.py:2"]
        assert f.path == "wirepkg/consumer.py" and f.line == 15

    def test_dispatch_without_reject_path(self, found):
        f = found["no-reject-path:wirepkg/consumer.py:decode"]
        assert f.path == "wirepkg/consumer.py"
        # route() compares a literal, not an F_* name — no dispatch
        # finding for it, and nothing else unexpected fired.
        assert "no-reject-path:wirepkg/consumer.py:route" not in found
        assert len(found) == 7, sorted(found)

    def test_wire_plane_magic_declaration(self):
        repo = Repo(os.path.join(FIXTURES, "wire"), rel_dirs=("wirepkg",))
        found = _by_key(wire_registry.check(
            repo, net_path="wirepkg/net.py", allowed_dupes={},
            wire_plane=("wirepkg/consumer.py",)))
        assert "wire-plane-magic:wirepkg/consumer.py:MAGIC_TWO" in found

    def test_allowed_dupe_suppresses_and_guards_drift(self):
        repo = Repo(os.path.join(FIXTURES, "wire"), rel_dirs=("wirepkg",))
        allow = {b"TSTA": {
            "files": frozenset({"wirepkg/net.py", "wirepkg/consumer.py"}),
            "reason": "fixture"}}
        found = _by_key(wire_registry.check(
            repo, net_path="wirepkg/net.py", allowed_dupes=allow,
            wire_plane=()))
        assert not any(k.startswith("dup-magic:") for k in found)
        # Drift guard: an allowed file that stops declaring the value.
        allow2 = {b"TSTB": {
            "files": frozenset({"wirepkg/net.py"}), "reason": "fixture"}}
        found2 = _by_key(wire_registry.check(
            repo, net_path="wirepkg/net.py", allowed_dupes=allow2,
            wire_plane=()))
        assert any(k.startswith("dupe-drift:wirepkg/net.py")
                   for k in found2)


class TestConfigCoverageChecker:
    @pytest.fixture()
    def found(self):
        repo = Repo(os.path.join(FIXTURES, "config_cov"),
                    rel_dirs=("confpkg",))
        return _by_key(config_coverage.check(
            repo, config_path="confpkg/config.py",
            doc_text="actor.num_actors and actor.documented_knob"))

    def test_ghost_attribute_read(self, found):
        f = found["ghost:actor.ghost_knob"]
        assert f.path == "confpkg/reader.py" and f.line == 6

    def test_ghost_getattr_read(self, found):
        f = found["ghost:actor.ghost_via_getattr"]
        assert f.path == "confpkg/reader.py" and f.line == 7

    def test_undocumented_knob(self, found):
        f = found["undocumented:actor.ghost_target"]
        assert f.path == "confpkg/config.py" and f.line == 11

    def test_declared_and_documented_reads_are_clean(self, found):
        assert "ghost:actor.num_actors" not in found
        assert "undocumented:actor.num_actors" not in found
        assert len(found) == 3


class TestMetricsDocChecker:
    def test_undocumented_names_fire_documented_dont(self):
        repo = Repo(os.path.join(FIXTURES, "metrics"),
                    rel_dirs=("metricspkg",))
        found = _by_key(metrics_doc.check(
            repo, doc_text="the doc mentions `good/counter` only"))
        g = found["instrument:bad/undocumented_gauge"]
        assert g.path == "metricspkg/bad_metrics.py" and g.line == 6
        s = found["section:ghost_section"]
        assert s.line == 7
        assert "instrument:good/counter" not in found
        assert len(found) == 2

    def test_doc_section_keys_parses_real_doc(self):
        keys = metrics_doc.doc_section_keys("## Supervisor schema")
        assert "respawns" in keys and "watchdog" in keys


class TestShmDisciplineChecker:
    def test_raw_create_fires_attach_does_not(self):
        repo = Repo(os.path.join(FIXTURES, "shm"), rel_dirs=("shmpkg",))
        found = shm_discipline.check(repo, blessed="elsewhere.py")
        assert len(found) == 1
        f = found[0]
        assert f.checker == "shm-discipline"
        assert f.path == "shmpkg/raw_shm.py" and f.line == 7
        assert f.key == "raw-create:shmpkg/raw_shm.py:make"

    def test_blessed_module_is_exempt(self):
        repo = Repo(os.path.join(FIXTURES, "shm"), rel_dirs=("shmpkg",))
        assert shm_discipline.check(
            repo, blessed="shmpkg/raw_shm.py") == []


class TestTypedErrorsChecker:
    def test_bare_and_unjustified_fire_justified_and_narrow_dont(self):
        repo = Repo(os.path.join(FIXTURES, "errors"), rel_dirs=("errpkg",))
        found = _by_key(typed_errors.check(repo, dirs=("errpkg",)))
        b = found["bare-except:errpkg/bad_except.py:decode:0"]
        assert b.line == 8
        s = found["silent-swallow:errpkg/bad_except.py:cleanup:0"]
        assert s.line == 15
        assert len(found) == 2, sorted(found)

    def test_out_of_scope_dirs_are_ignored(self):
        repo = Repo(os.path.join(FIXTURES, "errors"), rel_dirs=("errpkg",))
        assert typed_errors.check(repo, dirs=("otherdir",)) == []


# ---------------------------------------------------------------------------
# Baseline protocol.
# ---------------------------------------------------------------------------


class TestBaselineProtocol:
    def test_reasonless_entry_rejected(self, tmp_path):
        p = tmp_path / "baseline.json"
        p.write_text(json.dumps(
            {"entries": [{"checker": "x", "key": "y", "reason": "  "}]}))
        with pytest.raises(ValueError, match="no reason"):
            analysis.load_baseline(str(p))

    def test_suppression_and_stale_reporting(self, tmp_path):
        f1 = analysis.Finding("c", "a.py", 1, "k1", "m1")
        f2 = analysis.Finding("c", "b.py", 2, "k2", "m2")
        p = tmp_path / "baseline.json"
        p.write_text(json.dumps({"entries": [
            {"checker": "c", "key": "k1", "reason": "known-WAI"},
            {"checker": "c", "key": "gone", "reason": "fixed long ago"},
        ]}))
        result = analysis.apply_baseline(
            [f1, f2], analysis.load_baseline(str(p)))
        assert [f.key for f in result.new] == ["k2"]
        assert [f.key for f in result.suppressed] == ["k1"]
        assert [e["key"] for e in result.stale_baseline] == ["gone"]
        assert not result.ok

    def test_committed_baseline_loads_and_every_entry_has_reason(self):
        analysis.load_baseline()        # raises on a malformed commit


# ---------------------------------------------------------------------------
# The repo itself: the pytest twin of verify gate 12.
# ---------------------------------------------------------------------------


class TestRepoIsClean:
    def test_committed_tree_lints_clean(self):
        repo = Repo(REPO)
        findings = analysis.run_all(repo)
        result = analysis.apply_baseline(findings, analysis.load_baseline())
        assert result.ok, "NEW lint findings:\n" + "\n".join(
            f.render() for f in result.new)

    def test_cli_json_mode_clean_and_fast(self):
        proc = subprocess.run(
            [sys.executable, "-m", "tools.lint", "--json"],
            cwd=REPO, capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        payload = json.loads(proc.stdout)
        assert payload["ok"] is True
        assert payload["files_scanned"] > 50

    @pytest.mark.parametrize("module", [
        m for m in IMPORT_LIGHT_CONTRACT])
    def test_contracted_module_is_dynamically_jax_free(self, module):
        """The runtime twin of the static walk: import each contracted
        module in a fresh interpreter and assert no heavy lib loaded."""
        code = (
            "import sys, importlib; "
            f"importlib.import_module({module!r}); "
            "heavy = [m for m in ('jax', 'jaxlib', 'flax', 'optax') "
            "if m in sys.modules]; "
            "assert not heavy, f'heavy imports loaded: {heavy}'"
        )
        proc = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                              capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, (module, proc.stderr)


# ---------------------------------------------------------------------------
# Doc-schema pins absorbed from test_obs.py: cheap stats dicts compared
# against docs/METRICS.md via the analyzer's shared parser.  (The pins
# needing a live training run stay in test_obs.py / test_replay_svc.py /
# test_central_inference.py, on the same parser.)
# ---------------------------------------------------------------------------


class TestDocSchemaDicts:
    def test_net_section_matches_doc(self):
        from ape_x_dqn_tpu.runtime.net import NetTransport

        doc = metrics_doc.doc_section_keys("## Net transport schema")
        assert doc, "Net transport schema doc section missing"
        tr = NetTransport()
        try:
            stats = tr.stats()
        finally:
            tr.close()
        assert set(doc) == set(stats), set(doc) ^ set(stats)

    def test_serving_net_section_matches_doc(self):
        from ape_x_dqn_tpu.serving.net_server import ServingNetServer

        class _Stub:
            param_version = 0

            def submit(self, obs):
                raise AssertionError("never called")

        doc = metrics_doc.doc_section_keys("## Serving net schema")
        assert doc, "Serving net schema doc section missing"
        srv = ServingNetServer(_Stub())
        try:
            stats = srv.stats()
        finally:
            srv.close()
        assert set(doc) == set(stats), set(doc) ^ set(stats)

    def test_serving_router_section_matches_doc(self):
        from ape_x_dqn_tpu.serving.router import ServingRouter

        doc = metrics_doc.doc_section_keys("## Serving router schema")
        assert doc, "Serving router schema doc section missing"
        router = ServingRouter(port=0)
        try:
            stats = router.stats()
        finally:
            router.close()
        assert set(doc) == set(stats), set(doc) ^ set(stats)

    def test_replay_tier_section_matches_doc(self, tmp_path):
        import numpy as np

        from ape_x_dqn_tpu.replay.dedup import DedupReplay
        from ape_x_dqn_tpu.types import DedupChunk

        doc = metrics_doc.doc_section_keys("## Replay tier schema")
        assert doc, "Replay tier schema doc section missing"
        rep = DedupReplay(64, (6, 6, 1), hot_frame_budget_bytes=128,
                          spill_dir=str(tmp_path), spill_span_frames=4)
        r = np.random.default_rng(0)
        rep.add(
            (np.abs(r.normal(size=8)) + 0.1).astype(np.float32),
            DedupChunk(
                frames=r.integers(0, 255, (9, 6, 6, 1), dtype=np.uint8),
                obs_ref=np.arange(8, dtype=np.int32),
                next_ref=np.arange(1, 9, dtype=np.int32),
                action=r.integers(0, 3, 8).astype(np.int32),
                reward=r.normal(size=8).astype(np.float32),
                discount=np.full(8, 0.9, np.float32),
                source=1, chunk_seq=0, prev_frames=9,
            ),
        )
        rep.spill_cold()
        rep.sample(8, rng=np.random.default_rng(1))  # faults cold spans
        stats = rep.tier_stats()
        assert stats["fault_reads"] > 0
        assert set(doc) == set(stats), set(doc) ^ set(stats)
        for key in ("count", "mean_ms", "p50_ms", "p95_ms", "p99_ms",
                    "max_ms"):
            assert key in stats["fault_ms"], key
