"""Double-Q target / TD loss / priority unit tests."""

import jax
import jax.numpy as jnp
import numpy as np

from ape_x_dqn_tpu.ops import losses


def test_double_q_uses_online_argmax_target_eval():
    q_online = jnp.asarray([[1.0, 5.0, 2.0]])   # argmax = 1
    q_target = jnp.asarray([[10.0, 20.0, 30.0]])
    t = losses.double_q_target(q_online, q_target, jnp.asarray([1.0]), jnp.asarray([0.5]))
    # 1.0 + 0.5 * q_target[argmax q_online] = 1 + 0.5*20
    np.testing.assert_allclose(np.asarray(t), [11.0])


def test_zero_discount_means_no_bootstrap():
    q = jnp.ones((2, 4)) * 100.0
    t = losses.double_q_target(q, q, jnp.asarray([3.0, -1.0]), jnp.zeros(2))
    np.testing.assert_allclose(np.asarray(t), [3.0, -1.0])


def test_max_q_target():
    q = jnp.asarray([[1.0, 9.0]])
    t = losses.max_q_target(q, jnp.asarray([1.0]), jnp.asarray([0.1]))
    np.testing.assert_allclose(np.asarray(t), [1.9], rtol=1e-6)


def test_td_error_gathers_taken_action():
    q = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
    d = losses.td_error(q, jnp.asarray([1, 0]), jnp.asarray([0.0, 0.0]))
    np.testing.assert_allclose(np.asarray(d), [2.0, 3.0])


def test_huber_matches_quadratic_inside_kappa():
    d = jnp.asarray([-0.5, 0.5, 2.0])
    h = losses.huber(d, kappa=1.0)
    np.testing.assert_allclose(np.asarray(h)[:2], 0.5 * 0.25, rtol=1e-6)
    np.testing.assert_allclose(float(h[2]), 0.5 + 1.0 * (2.0 - 1.0), rtol=1e-6)


def test_is_weights_scale_loss():
    d = jnp.asarray([1.0, 1.0])
    unweighted = losses.td_loss(d, None, kind="squared")
    weighted = losses.td_loss(d, jnp.asarray([2.0, 2.0]), kind="squared")
    np.testing.assert_allclose(float(weighted), 2 * float(unweighted))


def test_priorities_per_transition_not_collapsed():
    # Reference collapses batch priorities to one value (SURVEY §2.8).
    d = jnp.asarray([1.0, -2.0, 3.0])
    p = losses.priorities_from_td(d, epsilon=0.0)
    np.testing.assert_allclose(np.asarray(p), [1.0, 2.0, 3.0])
    assert len(set(np.asarray(p).tolist())) == 3


def test_target_is_stop_gradiented():
    def f(q_next):
        t = losses.double_q_target(q_next, q_next, jnp.zeros(1), jnp.ones(1))
        return jnp.sum(t)

    g = jax.grad(f)(jnp.asarray([[1.0, 2.0]]))
    np.testing.assert_allclose(np.asarray(g), np.zeros((1, 2)))
