"""Fleet supervision (runtime/supervisor.py): the policy layer's contract.

Policies are pure and clock-injected, so the units drive time explicitly:
backoff doubling + jitter bounds + crash-loop quarantine (RespawnPolicy),
the degrade-before-wedge ladder (LearnerWatchdog), stale-params shedding
(ServingStalenessPolicy on a real PolicyServer), and the fallback-restore
counter fed by checkpoint_inc's module-level event channel.  One
process-pool integration pins the expensive end: a worker killed past the
crash-loop budget is QUARANTINED (fleet shrinks, no fatal error, the pool
finishes) instead of hot-looping respawns — plus the satellite fix that a
dead worker is never respawned faster than actor.respawn_min_interval_s
even with no policy attached.
"""

import os
import signal
import time

import numpy as np
import pytest

from ape_x_dqn_tpu.config import ApexConfig, SupervisorConfig
from ape_x_dqn_tpu.runtime.supervisor import (
    QUARANTINE,
    RESPAWN,
    WAIT,
    FleetSupervisor,
    LearnerWatchdog,
    RespawnPolicy,
    ServingStalenessPolicy,
)


class TestRespawnPolicy:
    def test_backoff_doubles_and_caps(self):
        p = RespawnPolicy(base_s=1.0, max_s=4.0, jitter=0.0,
                          window_s=1000.0, budget=10, seed=0)
        t = 0.0
        expected = [1.0, 2.0, 4.0, 4.0]  # doubling, capped at max_s
        for want in expected:
            p.on_death(3, now=t)
            assert p.decide(3, now=t) == WAIT
            assert p.backoff_remaining(3, now=t) == pytest.approx(want)
            assert p.decide(3, now=t + want + 1e-6) == RESPAWN
            t += want + 1.0

    def test_jitter_bounded_and_seeded(self):
        a = RespawnPolicy(base_s=1.0, max_s=30.0, jitter=0.25, seed=7)
        b = RespawnPolicy(base_s=1.0, max_s=30.0, jitter=0.25, seed=7)
        for wid in range(16):
            a.on_death(wid, now=0.0)
            b.on_death(wid, now=0.0)
            ra = a.backoff_remaining(wid, now=0.0)
            assert 0.75 <= ra <= 1.25  # +/- jitter fraction of base
            # Same seed, same jitter stream: the schedule reproduces.
            assert ra == b.backoff_remaining(wid, now=0.0)

    def test_crash_loop_budget_quarantines(self):
        p = RespawnPolicy(base_s=0.0, max_s=0.0, jitter=0.0,
                          window_s=10.0, budget=3, seed=0)
        for i in range(3):
            assert p.on_death(5, now=float(i)) == WAIT
        assert p.on_death(5, now=3.0) == QUARANTINE
        assert p.decide(5, now=99.0) == QUARANTINE  # permanent
        assert 5 in p.quarantined

    def test_window_slides_deaths_expire(self):
        p = RespawnPolicy(base_s=0.0, max_s=0.0, jitter=0.0,
                          window_s=5.0, budget=2, seed=0)
        assert p.on_death(1, now=0.0) == WAIT
        assert p.on_death(1, now=1.0) == WAIT
        # Both deaths aged out of the window: streak resets, no quarantine.
        assert p.on_death(1, now=100.0) == WAIT
        assert 1 not in p.quarantined
        assert p.state(now=100.0)["1"]["deaths_in_window"] == 1


class TestLearnerWatchdog:
    def test_degrade_then_wedge_ladder(self):
        progress = [0]
        degraded = []
        events = []
        w = LearnerWatchdog(
            lambda: progress[0], lambda: degraded.append(1),
            stall_deadline_s=10.0, wedge_deadline_s=20.0,
            on_event=lambda kind, **f: events.append(kind),
        )
        assert w.check(now=0.0) == "ok"
        assert w.check(now=9.0) == "ok"          # inside the deadline
        assert w.check(now=11.0) == "degraded"   # stalled past it
        assert degraded == [1] and w.degradations == 1
        assert w.check(now=30.0) == "degraded"   # wedge clock restarted
        assert w.check(now=32.0) == "wedged"     # 21 s past the degrade
        assert w.age_s() == float("inf")         # /healthz 503 signal
        assert events == ["pipeline_degraded", "run_wedged"]

    def test_progress_resets_ladder(self):
        progress = [0]
        w = LearnerWatchdog(lambda: progress[0], None,
                            stall_deadline_s=10.0, wedge_deadline_s=10.0)
        assert w.check(now=0.0) == "ok"
        assert w.check(now=11.0) == "degraded"
        progress[0] = 1                           # the degrade unstuck it
        assert w.check(now=12.0) == "ok"
        assert w.age_s() == 0.0
        assert w.check(now=21.0) == "ok"          # deadline re-anchored

    def test_unreadable_progress_counts_as_stalled(self):
        def boom():
            raise RuntimeError("learner gone")

        w = LearnerWatchdog(boom, None, stall_deadline_s=5.0,
                            wedge_deadline_s=5.0)
        w.check(now=0.0)
        assert w.check(now=6.0) == "degraded"


class TestFleetSupervisorCounters:
    def _sup(self, **over):
        cfg = SupervisorConfig(**over)
        return FleetSupervisor(cfg, emit=None, seed=0)

    def test_death_respawn_quarantine_accounting(self):
        sup = self._sup(respawn_backoff_base_s=0.0,
                        respawn_backoff_max_s=0.0, respawn_jitter=0.0,
                        crash_loop_budget=2)
        assert sup.on_worker_death(0, "boom", now=0.0) == WAIT
        assert sup.decide_respawn(0, now=0.1) == RESPAWN
        assert int(sup.respawns.value) == 1
        sup.on_worker_death(0, "boom", now=0.2)
        assert sup.on_worker_death(0, "boom", now=0.3) == QUARANTINE
        assert int(sup.quarantines.value) == 1
        state = sup.state()
        assert state["quarantined"] == [0]
        kinds = [e["kind"] for e in sup.events]
        assert "worker_quarantined" in kinds and "worker_respawn" in kinds

    def test_fallback_events_drained_at_construction(self):
        from ape_x_dqn_tpu.utils.checkpoint_inc import (
            FALLBACK_EVENTS,
            consume_fallback_events,
        )

        consume_fallback_events()  # isolate from earlier tests' restores
        FALLBACK_EVENTS.append(
            {"event": "degraded_restore", "fallback": "previous_generation",
             "generation": 1, "step": 40}
        )
        sup = self._sup()
        assert int(sup.fallback_restores.value) == 1
        assert not FALLBACK_EVENTS  # consumed, not double-counted

    def test_registry_rows_and_provider(self):
        sup = self._sup()
        snap = sup.registry.snapshot()
        for key in ("supervisor/respawns", "supervisor/quarantines",
                    "supervisor/degradations",
                    "supervisor/fallback_restores"):
            assert key in snap, key
        assert "supervisor" in snap
        text = sup.registry.prometheus_text()
        assert "apex_supervisor_respawns_total" in text


class TestServingStaleness:
    def _server(self, stale_after_s):
        import jax
        import jax.numpy as jnp

        from ape_x_dqn_tpu.models.dueling import DuelingMLP
        from ape_x_dqn_tpu.serving.server import PolicyServer

        net = DuelingMLP(num_actions=3, hidden_sizes=(8,))
        params = net.init(jax.random.PRNGKey(0),
                          jnp.zeros((1, 4), jnp.uint8))
        server = PolicyServer(net, params=params, max_batch=2,
                              max_wait_ms=1.0)
        server.start()
        return server

    def test_stale_sheds_typed_and_recovers(self):
        from ape_x_dqn_tpu.serving.batcher import ServerOverloaded

        server = self._server(stale_after_s=0.05)
        try:
            policy = ServingStalenessPolicy(server, stale_after_s=0.05)
            obs = np.zeros((4,), np.uint8)
            assert server.act(obs, timeout=10.0).action in (0, 1, 2)
            time.sleep(0.1)                      # params now stale
            assert policy.check() is True and server.degraded
            assert policy.age_s() > 0.05         # the /healthz age fn
            with pytest.raises(ServerOverloaded, match="stale"):
                server.submit(obs)
            shed_before = server.stats()["shed_total"]
            assert shed_before >= 1
            assert server.stats()["degraded"] is True
            # A fresh snapshot adoption recovers automatically.
            server._live = (server._live[0], server._live[1] + 1,
                            time.monotonic())
            assert policy.check() is False and not server.degraded
            assert server.act(obs, timeout=10.0).action in (0, 1, 2)
            assert policy.transitions == 2       # degrade + recover
        finally:
            server.close()

    def test_supervisor_attach_serving_counts_degradations(self):
        server = self._server(stale_after_s=0.05)
        try:
            sup = FleetSupervisor(SupervisorConfig(), emit=None, seed=0)
            policy = sup.attach_serving(server, stale_after_s=0.05)
            time.sleep(0.1)
            sup.tick()
            assert server.degraded
            assert int(sup.degradations.value) == 1
            assert sup.state()["serving_degraded"] is True
            assert policy in sup.serving_policies
        finally:
            server.close()


@pytest.mark.slow
class TestPoolSupervision:
    """The expensive end: real worker processes under the policy layer."""

    def _cfg(self):
        cfg = ApexConfig()
        cfg.network = "mlp"
        cfg.env.name = "chain:6"
        cfg.actor.num_actors = 2
        cfg.actor.T = 1_000_000
        cfg.actor.flush_every = 8
        cfg.actor.sync_every = 32
        cfg.actor.respawn_min_interval_s = 0.1
        return cfg

    def _drain_until(self, pool, cond, timeout_s):
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            pool.supervise()
            pool.poll(max_items=64, timeout=0.1)
            if cond():
                return True
        return False

    def test_crash_loop_quarantines_and_fleet_shrinks(self):
        from ape_x_dqn_tpu.runtime.process_actors import (
            ProcessActorPool,
            network_and_template,
        )

        cfg = self._cfg()
        scfg = SupervisorConfig(
            respawn_backoff_base_s=0.1, respawn_backoff_max_s=0.3,
            respawn_jitter=0.0, crash_loop_window_s=300.0,
            crash_loop_budget=1,
        )
        sup = FleetSupervisor(scfg, emit=None, seed=0)
        pool = ProcessActorPool(cfg, num_workers=2, max_restarts=3)
        sup.attach_pool(pool)
        assert pool.respawn_policy is sup
        try:
            _, _, params = network_and_template(cfg)
            pool.publish(params)
            pool.start()
            assert self._drain_until(
                pool, lambda: set(pool.last_versions) == {0, 1}, 240
            )
            # Budget 1: first kill respawns, second quarantines.
            for _ in range(2):
                p = pool._procs[0]
                steps = pool._steps_by_worker.get(0, 0)
                os.kill(p.pid, signal.SIGKILL)
                p.join(10.0)
                assert self._drain_until(
                    pool,
                    lambda: 0 in pool.quarantined
                    or (pool._procs[0].is_alive()
                        and pool._steps_by_worker.get(0, 0) > steps),
                    240,
                )
            assert 0 in pool.quarantined
            assert int(sup.quarantines.value) == 1
            assert not pool.worker_errors       # shrank, did not fail
            # The survivor keeps feeding — the fleet runs degraded.
            before = pool._steps_by_worker.get(1, 0)
            assert self._drain_until(
                pool, lambda: pool._steps_by_worker.get(1, 0) > before, 240
            )
            # A quarantined worker counts toward completion accounting.
            assert not pool.finished  # worker 1 still running
        finally:
            pool.stop()

    def test_min_respawn_interval_floors_legacy_pool(self):
        """Satellite: even with NO policy attached, a dead worker is not
        respawned before actor.respawn_min_interval_s — a deterministic
        startup crash cannot spin the pool."""
        from ape_x_dqn_tpu.runtime.process_actors import (
            ProcessActorPool,
            network_and_template,
        )

        cfg = self._cfg()
        cfg.actor.respawn_min_interval_s = 2.0
        pool = ProcessActorPool(cfg, num_workers=2, max_restarts=5)
        try:
            _, _, params = network_and_template(cfg)
            pool.publish(params)
            pool.start()
            assert self._drain_until(
                pool, lambda: set(pool.last_versions) == {0, 1}, 240
            )
            p = pool._procs[0]
            os.kill(p.pid, signal.SIGKILL)
            p.join(10.0)
            killed_at = time.monotonic()
            # Hammer supervise(): the respawn must wait out the floor.
            while pool.restarts == 0 \
                    and time.monotonic() - killed_at < 60.0:
                pool.supervise()
                pool.poll(max_items=16, timeout=0.02)
            assert pool.restarts == 1
            spawned_at = pool._last_spawn[0]
            assert spawned_at - killed_at >= 2.0 - 0.25, (
                "respawn beat the minimum interval floor"
            )
        finally:
            pool.stop()
