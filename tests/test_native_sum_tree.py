"""Native C++ sum-tree vs. the numpy reference implementation.

The numpy SumTree is the executable spec; the native core must agree with it
bit-for-bit on identical operation sequences (same stratified targets)."""

import numpy as np
import pytest

from ape_x_dqn_tpu.replay.native import (
    NativeSumTree,
    default_sum_tree_cls,
    native_available,
    native_error,
)
from ape_x_dqn_tpu.replay.sum_tree import SumTree
from ape_x_dqn_tpu.replay.buffer import PrioritizedReplay

pytestmark = pytest.mark.skipif(
    not native_available(), reason=f"native core unavailable: {native_error()}"
)


def test_agrees_with_numpy_on_random_ops(rng):
    cap = 257  # non-power-of-two
    a, b = SumTree(cap), NativeSumTree(cap)
    for _ in range(50):
        n = int(rng.integers(1, 64))
        idx = rng.integers(0, cap, n)
        pri = rng.random(n) * 10
        a.set(idx, pri)
        b.set(idx, pri)
        assert np.isclose(a.total, b.total)
        probe = rng.integers(0, cap, 32)
        np.testing.assert_allclose(a.get(probe), b.get(probe))
        targets = rng.random(128) * a.total
        np.testing.assert_array_equal(a.sample(targets), b.sample(targets))


def test_duplicate_last_write_wins():
    t = NativeSumTree(8)
    t.set(np.array([3, 3, 3]), np.array([1.0, 9.0, 4.0]))
    assert t.get(np.array([3]))[0] == 4.0
    assert np.isclose(t.total, 4.0)


def test_error_paths():
    t = NativeSumTree(4)
    with pytest.raises(IndexError):
        t.set(np.array([7]), np.array([1.0]))
    with pytest.raises(ValueError):
        t.set(np.array([0]), np.array([-2.0]))
    with pytest.raises(ValueError):
        t.set(np.array([0]), np.array([np.nan]))
    with pytest.raises(ValueError):
        t.sample_stratified(4, np.random.default_rng(0))


def test_replay_with_native_tree(rng):
    from tests.test_replay import make_batch

    rep = PrioritizedReplay(
        64, (4, 4, 1), sum_tree_cls=default_sum_tree_cls()
    )
    rep.add(rng.random(32) + 0.1, make_batch(32))
    out = rep.sample(16, rng=rng)
    assert out.transition.obs.shape == (16, 4, 4, 1)
    rep.update_priorities(out.indices, rng.random(16) + 0.1)


def test_stratified_distribution(rng):
    t = NativeSumTree(16)
    pri = np.arange(1.0, 17.0)
    t.set(np.arange(16), pri)
    idx = t.sample_stratified(100_000, rng)
    freq = np.bincount(idx, minlength=16) / 100_000
    np.testing.assert_allclose(freq, pri / pri.sum(), atol=6e-3)
