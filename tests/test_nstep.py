"""n-step return math vs. a slow oracle (SURVEY §4 test level 1)."""

import jax.numpy as jnp
import numpy as np
import pytest

from ape_x_dqn_tpu.ops.nstep import (
    build_nstep_transitions,
    nstep_returns,
    nstep_returns_reference,
)


@pytest.mark.parametrize("n", [1, 2, 3, 5])
def test_nstep_returns_match_oracle(rng, n):
    T = 37
    rewards = rng.normal(size=T).astype(np.float32)
    dones = rng.random(T) < 0.15
    gamma = 0.99
    discounts = (gamma * (1.0 - dones)).astype(np.float32)
    got_r, got_d = nstep_returns(jnp.asarray(rewards), jnp.asarray(discounts), n)
    exp_r, exp_d = nstep_returns_reference(rewards, discounts, n)
    np.testing.assert_allclose(np.asarray(got_r), exp_r, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(got_d), exp_d, rtol=1e-5)


def test_bootstrap_discount_is_gamma_to_the_n():
    # The reference stores gamma^(n-1) (SURVEY §2.8); we must store gamma^n.
    n, gamma = 3, 0.99
    rewards = jnp.zeros(n)
    discounts = jnp.full((n,), gamma)
    _, boot = nstep_returns(rewards, discounts, n)
    np.testing.assert_allclose(float(boot[0]), gamma**n, rtol=1e-6)
    assert not np.isclose(float(boot[0]), gamma ** (n - 1))


def test_terminal_masks_bootstrap():
    # A terminal inside the window must zero the bootstrap discount and
    # truncate the return (no bootstrapping through episode ends).
    n, gamma = 3, 0.9
    rewards = jnp.asarray([1.0, 1.0, 1.0, 7.0])
    discounts = jnp.asarray([gamma, 0.0, gamma, gamma])  # step 1 terminates
    rets, boot = nstep_returns(rewards, discounts, n)
    # window starting at 0: r0 + g*r1 + g*0*r2 = 1 + 0.9
    np.testing.assert_allclose(float(rets[0]), 1.0 + gamma, rtol=1e-6)
    assert float(boot[0]) == 0.0


@pytest.mark.parametrize("stride", [1, 3])
def test_build_nstep_transitions_shapes_and_alignment(rng, stride):
    T, n = 12, 3
    obs = rng.integers(0, 255, size=(T, 4, 4, 1)).astype(np.uint8)
    tail = rng.integers(0, 255, size=(4, 4, 1)).astype(np.uint8)  # S_T only
    actions = rng.integers(0, 4, size=T).astype(np.int32)
    rewards = rng.normal(size=T).astype(np.float32)
    discounts = np.full(T, 0.99, np.float32)
    tr = build_nstep_transitions(
        jnp.asarray(obs), jnp.asarray(actions), jnp.asarray(rewards),
        jnp.asarray(discounts), jnp.asarray(tail), n=n, stride=stride,
    )
    starts = np.arange(0, T - n + 1, stride)
    assert tr.action.shape == (len(starts),)
    np.testing.assert_array_equal(np.asarray(tr.obs), obs[starts])
    np.testing.assert_array_equal(np.asarray(tr.action), actions[starts])
    # next_obs for start t is obs[t+n] (from concat(obs, tail))
    all_obs = np.concatenate([obs, tail[None]], axis=0)
    np.testing.assert_array_equal(np.asarray(tr.next_obs), all_obs[starts + n])
