"""Frame-dedup replay: equal-semantics vs the double-store + dedup-only
edges (round-4 verdict item 1a).

Levels:
  1. EMISSION — ActorFleet(emit_dedup=True) decodes (types.materialize_dedup)
     to byte-identical transitions + priorities vs the dense fleet, across
     truncation-heavy, terminal, pixel, and strided workloads.
  2. STORE — DedupReplay fed the dedup stream is observationally identical
     to PrioritizedReplay fed the materialized stream: same slots, same
     samples, same IS weights, same priority updates, through FIFO wrap.
  3. DEDUP EDGES — frame-ring early death (sweep), carry-gap drops,
     restamp-resurrection guard, checkpoint roundtrip with a wrapped ring.
"""

import numpy as np
import pytest

from ape_x_dqn_tpu.replay import DedupReplay, PrioritizedReplay
from ape_x_dqn_tpu.replay.sum_tree import SumTree
from ape_x_dqn_tpu.types import DedupChunk, materialize_dedup

OBS = (3, 3, 1)


def frame(seq: int) -> np.ndarray:
    """A frame whose content encodes its global sequence number."""
    return np.full(OBS, seq % 251, np.uint8)


def make_chunk(source: int, chunk_seq: int, fbase: int, n_tx: int = 4,
               carry: int = 0, prev_frames: int = 0, extras: int = 0):
    """A hand-built dedup chunk: ``n_tx + carry`` transitions over
    ``n_tx + 1 + extras`` fresh frames (each S_{t+n} = next fresh frame;
    ``carry`` rows reference the previous chunk's tail)."""
    U = n_tx + 1 + extras
    frames = np.stack([frame(fbase + i) for i in range(U)])
    obs_ref = np.concatenate([
        -np.arange(carry, 0, -1, dtype=np.int32),       # carry rows first
        np.arange(n_tx, dtype=np.int32),
    ])
    next_ref = np.concatenate([
        np.zeros(carry, np.int32),
        np.arange(1, n_tx + 1, dtype=np.int32),
    ])
    m = n_tx + carry
    rng = np.random.default_rng(chunk_seq * 977 + source % 1000)
    return DedupChunk(
        frames=frames,
        obs_ref=obs_ref,
        next_ref=next_ref,
        action=rng.integers(0, 4, m).astype(np.int32),
        reward=rng.normal(size=m).astype(np.float32),
        discount=np.full(m, 0.97, np.float32),
        source=source,
        chunk_seq=chunk_seq,
        prev_frames=prev_frames,
    )


def fleet_pair(env_fn, obs_dim, n_step=3, flush=5, steps=60,
               emission="overlapping", num=3):
    import jax

    from ape_x_dqn_tpu.actors import ActorFleet, LocalParamSource
    from ape_x_dqn_tpu.models.dueling import DuelingMLP

    net = DuelingMLP(num_actions=env_fn().num_actions, hidden_sizes=(8,))
    params = net.init(jax.random.PRNGKey(0), np.zeros((1, *obs_dim), np.uint8))
    out = []
    for dedup in (False, True):
        fleet = ActorFleet(
            [env_fn] * num, net, n_step=n_step, flush_every=flush, seed=7,
            emission=emission, emit_dedup=dedup,
        )
        fleet.sync_params(LocalParamSource(params))
        chunks, _ = fleet.collect(steps)
        out.append(chunks)
    return out


class TestEmissionEquivalence:
    @pytest.mark.parametrize("env_spec,obs_dim,kw", [
        ("loop:7", (4,), {}),                          # truncation-heavy
        ("chain:5", (5,), {}),                         # terminals + trunc
        ("catch", (10, 5, 1), dict(flush=16, steps=96)),
        ("chain:5", (5,), dict(emission="strided", flush=6)),
    ])
    def test_dedup_decodes_to_dense(self, env_spec, obs_dim, kw):
        from ape_x_dqn_tpu.envs import make_env

        dense, dd = fleet_pair(lambda: make_env(env_spec), obs_dim, **kw)
        assert len(dense) == len(dd) and dense
        prev = None
        for i, (a, b) in enumerate(zip(dense, dd)):
            np.testing.assert_array_equal(a.priorities, b.priorities)
            assert b.transitions.chunk_seq == i
            mat = materialize_dedup(b.transitions, prev)
            for f in ("obs", "action", "reward", "discount", "next_obs"):
                np.testing.assert_array_equal(
                    getattr(a.transitions, f), getattr(mat, f),
                    err_msg=f"{f} diverged in chunk {i}",
                )
            prev = b.transitions

    def test_steady_state_frame_ratio_near_one(self):
        from ape_x_dqn_tpu.envs import make_env

        _, dd = fleet_pair(
            lambda: make_env("catch"), (10, 5, 1), flush=16, steps=160
        )
        tx = sum(c.transitions.action.shape[0] for c in dd)
        fr = sum(c.transitions.frames.shape[0] for c in dd)
        # The dedup win: ~1 frame per transition vs the double-store's 2.
        assert fr / tx < 1.15, (fr, tx)

    def test_grouped_emission_decodes_to_dense(self):
        """emit_dedup_groups=2: two independent sources per flush whose
        concatenation (in actor-column order) equals the dense chunk."""
        import jax

        from ape_x_dqn_tpu.actors import ActorFleet, LocalParamSource
        from ape_x_dqn_tpu.envs import make_env
        from ape_x_dqn_tpu.models.dueling import DuelingMLP

        net = DuelingMLP(num_actions=2, hidden_sizes=(8,))
        params = net.init(
            jax.random.PRNGKey(0), np.zeros((1, 5), np.uint8)
        )
        out = []
        for dedup, groups in ((False, 1), (True, 2)):
            fleet = ActorFleet(
                [lambda: make_env("chain:5")] * 5, net, n_step=3,
                flush_every=5, seed=7, emit_dedup=dedup,
                emit_dedup_groups=groups,
            )
            fleet.sync_params(LocalParamSource(params))
            chunks, _ = fleet.collect(40)
            out.append(chunks)
        dense, dd = out
        assert len(dd) == 2 * len(dense)
        prev = {}
        # Group bounds for 5 actors / 2 groups: [0, 2), [2, 5).
        for i, a in enumerate(dense):
            ga, gb = dd[2 * i].transitions, dd[2 * i + 1].transitions
            assert ga.source != gb.source
            mats = [
                materialize_dedup(g, prev.get(g.source)) for g in (ga, gb)
            ]
            S = a.transitions.action.shape[0] // 5
            dense_2d = {
                f: getattr(a.transitions, f).reshape(
                    S, 5, *getattr(a.transitions, f).shape[1:]
                )
                for f in ("obs", "action", "reward", "discount", "next_obs")
            }
            for f in dense_2d:
                np.testing.assert_array_equal(
                    dense_2d[f][:, :2].reshape(
                        -1, *dense_2d[f].shape[2:]
                    ),
                    getattr(mats[0], f), err_msg=f"{f} group 0 chunk {i}",
                )
                np.testing.assert_array_equal(
                    dense_2d[f][:, 2:].reshape(
                        -1, *dense_2d[f].shape[2:]
                    ),
                    getattr(mats[1], f), err_msg=f"{f} group 1 chunk {i}",
                )
            prio_2d = a.priorities.reshape(S, 5)
            np.testing.assert_array_equal(
                prio_2d[:, :2].reshape(-1), dd[2 * i].priorities
            )
            np.testing.assert_array_equal(
                prio_2d[:, 2:].reshape(-1), dd[2 * i + 1].priorities
            )
            prev[ga.source] = ga
            prev[gb.source] = gb

    def test_dedup_requires_flush_at_least_n(self):
        from ape_x_dqn_tpu.actors import ActorFleet
        from ape_x_dqn_tpu.envs import ChainMDP
        from ape_x_dqn_tpu.models.dueling import DuelingMLP

        net = DuelingMLP(num_actions=2, hidden_sizes=(8,))
        with pytest.raises(ValueError, match="dedup"):
            ActorFleet([ChainMDP] * 2, net, n_step=4, flush_every=3,
                       emit_dedup=True)


def mirrored_buffers(capacity=64, frame_ratio=2.0):
    dd = DedupReplay(capacity, OBS, sum_tree_cls=SumTree,
                     frame_ratio=frame_ratio)
    ds = PrioritizedReplay(capacity, OBS, sum_tree_cls=SumTree)
    return dd, ds


def feed_both(dd, ds, chunks, prio_rng):
    """Feed the dedup stream to DedupReplay and its materialization to the
    double-store; returns the per-chunk priorities used."""
    prev_by_src = {}
    for c in chunks:
        p = (np.abs(prio_rng.normal(size=c.action.shape[0])) + 0.1)
        i1 = dd.add(p, c)
        i2 = ds.add(p, materialize_dedup(c, prev_by_src.get(c.source)))
        np.testing.assert_array_equal(i1, i2)
        prev_by_src[c.source] = c


class TestStoreEquivalence:
    def chunk_stream(self, n_chunks=40, n_tx=4):
        """A contiguous single-source stream with cross-chunk carry."""
        out = []
        fbase = 0
        prev_U = 0
        for i in range(n_chunks):
            carry = 2 if i else 0
            c = make_chunk(11, i, fbase, n_tx=n_tx, carry=carry,
                           prev_frames=prev_U, extras=(i % 3 == 2))
            out.append(c)
            fbase += c.frames.shape[0]
            prev_U = c.frames.shape[0]
        return out

    def test_identical_samples_through_wrap(self):
        dd, ds = mirrored_buffers(capacity=64)
        # 40 chunks x ~5-6 rows ≈ 3-4x capacity: full FIFO wrap coverage.
        feed_both(dd, ds, self.chunk_stream(), np.random.default_rng(0))
        assert dd.size() == ds.size() == 64
        assert dd.stats["frame_dead"] == 0, "ratio 2.0 must never early-kill"
        for trial in range(5):
            r1, r2 = (np.random.default_rng(trial), np.random.default_rng(trial))
            b1 = dd.sample(16, beta=0.5, rng=r1)
            b2 = ds.sample(16, beta=0.5, rng=r2)
            np.testing.assert_array_equal(b1.indices, b2.indices)
            np.testing.assert_allclose(b1.is_weights, b2.is_weights)
            for f in ("obs", "action", "reward", "discount", "next_obs"):
                np.testing.assert_array_equal(
                    getattr(b1.transition, f), getattr(b2.transition, f), f
                )
            upd = np.abs(np.random.default_rng(100 + trial).normal(size=16)) + 0.05
            dd.update_priorities(b1.indices, upd)
            ds.update_priorities(b2.indices, upd)
        assert dd.max_priority() == pytest.approx(ds.max_priority())

    def test_memory_halves(self):
        dd, ds = mirrored_buffers(capacity=64, frame_ratio=1.25)
        assert dd.frames_nbytes() == pytest.approx(
            0.625 * (ds._obs.nbytes() + ds._next_obs.nbytes()), rel=0.02
        )


class TestDedupEdges:
    def test_frame_death_sweep_and_sample_consistency(self):
        """An undersized frame ring must invalidate (not corrupt): dead
        slots become unsampleable, and every sampled row's frames still
        match its own insertion-time refs."""
        rng = np.random.default_rng(3)
        dd = DedupReplay(64, OBS, sum_tree_cls=SumTree, frame_ratio=0.5)
        fbase, prev_U = 0, 0
        for i in range(30):
            c = make_chunk(5, i, fbase, n_tx=4, carry=2 if i else 0,
                           prev_frames=prev_U)
            dd.add(np.ones(c.action.shape[0]), c)
            fbase += c.frames.shape[0]
            prev_U = c.frames.shape[0]
        assert dd.stats["frame_dead"] > 0
        # Live mass only on frame-live rows; every sample's obs content
        # equals the frame seq it references (frame() encodes seq).
        for t in range(10):
            b = dd.sample(8, rng=np.random.default_rng(t))
            seqs = dd._obs_seq[b.indices]
            nxt = dd._next_seq[b.indices]
            fmin = dd._fcount - dd.frame_capacity
            assert (seqs >= fmin).all(), "sampled a frame-dead transition"
            np.testing.assert_array_equal(
                b.transition.obs, np.stack([frame(s) for s in seqs])
            )
            np.testing.assert_array_equal(
                b.transition.next_obs, np.stack([frame(s) for s in nxt])
            )

    def test_restamp_cannot_resurrect_dead_slot(self):
        dd = DedupReplay(64, OBS, sum_tree_cls=SumTree, frame_ratio=0.5)
        fbase, prev_U = 0, 0
        first_idx = None
        for i in range(30):
            c = make_chunk(5, i, fbase, n_tx=4, carry=2 if i else 0,
                           prev_frames=prev_U)
            idx = dd.add(np.ones(c.action.shape[0]), c)
            if first_idx is None:
                first_idx = idx.copy()
            fbase += c.frames.shape[0]
            prev_U = c.frames.shape[0]
        # Find a currently-dead slot and try to restamp it.
        dead = np.nonzero(~dd._alive[: dd.size()])[0]
        assert dead.size, "expected frame-dead slots at ratio 0.5"
        before = dd._tree.get(dead[:1])[0]
        dd.update_priorities(dead[:1], np.array([9.9]))
        assert dd._tree.get(dead[:1])[0] == before == 0.0

    def test_carry_gap_drops_only_carried_rows(self):
        dd = DedupReplay(64, OBS, sum_tree_cls=SumTree)
        c0 = make_chunk(7, 0, 0, n_tx=4)
        dd.add(np.ones(4), c0)
        # chunk_seq jumps 0 -> 2: the 2 carry rows must drop, the rest land.
        c2 = make_chunk(7, 2, c0.frames.shape[0], n_tx=4, carry=2,
                        prev_frames=c0.frames.shape[0])
        idx = dd.add(np.ones(6), c2)
        assert len(idx) == 4
        assert dd.stats["dropped_carry"] == 2
        assert dd.size() == 8
        # An unknown source with carry refs drops them too.
        c_alien = make_chunk(99, 5, 40, n_tx=3, carry=1, prev_frames=17)
        idx = dd.add(np.ones(4), c_alien)
        assert len(idx) == 3
        assert dd.stats["dropped_carry"] == 3

    def test_checkpoint_roundtrip_wrapped_ring(self):
        dd = DedupReplay(32, OBS, sum_tree_cls=SumTree, frame_ratio=1.5)
        fbase, prev_U = 0, 0
        for i in range(25):
            c = make_chunk(5, i, fbase, n_tx=4, carry=2 if i else 0,
                           prev_frames=prev_U)
            dd.add(np.full(c.action.shape[0], 0.3 + 0.01 * i), c)
            fbase += c.frames.shape[0]
            prev_U = c.frames.shape[0]
        snap = dd.state_dict()
        # npz-roundtrip the snapshot like the checkpoint layer does.
        import io

        buf = io.BytesIO()
        np.savez(buf, **snap)
        buf.seek(0)
        with np.load(buf) as z:
            snap = {k: z[k] for k in z.files}
        dd2 = DedupReplay(32, OBS, sum_tree_cls=SumTree, frame_ratio=1.5)
        dd2.load_state_dict(snap)
        b1 = dd.sample(16, rng=np.random.default_rng(5))
        b2 = dd2.sample(16, rng=np.random.default_rng(5))
        np.testing.assert_array_equal(b1.indices, b2.indices)
        for f in ("obs", "action", "reward", "discount", "next_obs"):
            np.testing.assert_array_equal(
                getattr(b1.transition, f), getattr(b2.transition, f), f
            )
        # A CONTINUING source resumes carry across the restore.
        c = make_chunk(5, 25, fbase, n_tx=4, carry=2, prev_frames=prev_U)
        idx = dd2.add(np.ones(6), c)
        assert len(idx) == 6 and dd2.stats["dropped_carry"] == 0

    def test_frame_capacity_mismatch_rejected(self):
        dd = DedupReplay(32, OBS, sum_tree_cls=SumTree, frame_ratio=1.5)
        dd.add(np.ones(4), make_chunk(5, 0, 0, n_tx=4))
        snap = dd.state_dict()
        other = DedupReplay(32, OBS, sum_tree_cls=SumTree, frame_ratio=2.0)
        with pytest.raises(ValueError, match="frame ring"):
            other.load_state_dict(snap)
        ds_style = PrioritizedReplay(32, OBS, sum_tree_cls=SumTree)
        with pytest.raises(ValueError, match="dedup"):
            dd.load_state_dict(ds_style.state_dict())


class TestDedupRuntimes:
    """replay.dedup=true through BOTH host-replay runtimes (the fused
    device runtimes are covered in test_fused_dedup): the deterministic
    sync driver and the async pipeline's deferred priority write-back
    against the liveness guard."""

    def test_single_process_driver_trains_on_dedup(self):
        from ape_x_dqn_tpu.config import ApexConfig
        from ape_x_dqn_tpu.replay import DedupReplay
        from ape_x_dqn_tpu.runtime import SingleProcessDriver

        cfg = ApexConfig()
        cfg.env.name = "chain:5"
        cfg.network = "mlp"
        cfg.actor.num_actors = 4
        cfg.actor.flush_every = 8
        cfg.learner.min_replay_mem_size = 64
        cfg.learner.optimizer = "adam"
        cfg.replay.capacity = 2048
        cfg.replay.dedup = True
        driver = SingleProcessDriver(cfg)
        assert isinstance(driver.replay, DedupReplay)
        for _ in range(30):
            res = driver.run_iteration()
        assert driver.learner_step > 0
        assert np.isfinite(res.loss)
        assert driver.replay.stats["dropped_carry"] == 0

    def test_async_pipeline_host_dedup_end_to_end(self):
        from ape_x_dqn_tpu.config import ApexConfig
        from ape_x_dqn_tpu.runtime.async_pipeline import AsyncPipeline
        from ape_x_dqn_tpu.utils.metrics import MetricLogger
        import io

        cfg = ApexConfig()
        cfg.env.name = "chain:5"
        cfg.network = "mlp"
        cfg.actor.num_actors = 4
        cfg.actor.T = 100_000
        cfg.actor.flush_every = 8
        cfg.actor.sync_every = 16
        cfg.learner.min_replay_mem_size = 64
        cfg.learner.optimizer = "adam"
        cfg.learner.publish_every = 10
        cfg.replay.capacity = 2048
        cfg.replay.dedup = True
        pipe = AsyncPipeline(
            cfg, logger=MetricLogger(stream=io.StringIO()), log_every=50
        )
        result = pipe.run(learner_steps=60, warmup_timeout=120.0)
        assert result["step"] >= 60
        assert np.isfinite(result["learner/loss"])
        assert pipe.comps.replay.stats["dropped_carry"] == 0
