"""Tiered replay (replay/tiered.py): the disk-spill cold frame store.

Contracts pinned here:
  * ``TieredFrameRing`` is BIT-EXACT with a dense ndarray under any
    interleaving of puts/gets/spills/faults (zeros for never-written
    slots included);
  * eviction is least-recently-sampled first and respects the hot
    budget; clean re-evictions write nothing;
  * a torn cold record is DETECTED (typed ``ColdSpanCorrupt``), never
    returned as frame data — at fault time and at restore time;
  * tiered DedupReplay / NativeDedupReplay sample, update, snapshot and
    delta-chain bit-exactly like their dense twins (the tier moves
    bytes, never the sampling law);
  * incremental bases reference cold spans by offset (no re-read of the
    cold tier) and restore O(hot) by adopting the spill file in place —
    across twins, including dense↔tiered cross-restores;
  * SIGKILL mid-spill leaves a spill file whose every record is either
    valid or detectably torn, and the committed chain still restores.
"""

import multiprocessing
import os
import signal
import time

import numpy as np
import pytest

from ape_x_dqn_tpu.replay.dedup import DedupReplay
from ape_x_dqn_tpu.replay.tiered import (
    ColdSpanCorrupt,
    ColdSpanStore,
    TieredFrameRing,
    TierEvictor,
)
from ape_x_dqn_tpu.types import DedupChunk
from ape_x_dqn_tpu.utils.checkpoint_inc import (
    ChunkCorrupt,
    IncrementalCheckpointer,
    load_incremental_replay,
)

OBS = (6, 6, 1)


def dchunk(src=1, seq=0, seed=0, M=16, obs=OBS):
    r = np.random.default_rng(seed * 7919 + src)
    return DedupChunk(
        frames=r.integers(0, 255, (M + 1, *obs), dtype=np.uint8),
        obs_ref=np.arange(M, dtype=np.int32),
        next_ref=np.arange(1, M + 1, dtype=np.int32),
        action=r.integers(0, 3, M).astype(np.int32),
        reward=r.normal(size=M).astype(np.float32),
        discount=np.full(M, 0.9, np.float32),
        source=src, chunk_seq=seq, prev_frames=M + 1,
    )


def prio(M=16, seed=0):
    r = np.random.default_rng(seed + 1000)
    return (np.abs(r.normal(size=M)) + 0.1).astype(np.float32)


def assert_same_state(s1, s2):
    assert set(s1) == set(s2), (set(s1) ^ set(s2))
    for k in s1:
        np.testing.assert_array_equal(
            np.asarray(s1[k]), np.asarray(s2[k]), err_msg=k
        )


def _native_or_skip():
    from ape_x_dqn_tpu.replay.native_dedup import (
        NativeDedupReplay,
        native_dedup_available,
        native_dedup_error,
    )

    if not native_dedup_available():
        pytest.skip(f"native core unavailable: {native_dedup_error()}")
    return NativeDedupReplay


def make_pair(kind, tmp_path, cap=128, budget=2048, span=4):
    """(dense twin, tiered twin) of one flavor sharing nothing."""
    if kind == "dedup":
        dense = DedupReplay(cap, OBS)
        tiered = DedupReplay(
            cap, OBS, hot_frame_budget_bytes=budget,
            spill_dir=str(tmp_path / "spill"), spill_span_frames=span,
        )
    else:
        cls = _native_or_skip()
        dense = cls(cap, OBS)
        tiered = cls(
            cap, OBS, hot_frame_budget_bytes=budget,
            spill_dir=str(tmp_path / "spill"), spill_span_frames=span,
        )
    return dense, tiered


class TestColdSpanStore:
    def test_roundtrip_and_offset_addressing(self, tmp_path):
        store = ColdSpanStore(str(tmp_path / "c.cold"), 4, 64)
        off_a, crc = store.write(2, 0, b"x" * 64)
        assert store.read(off_a, sid=2, want_crc=crc) == b"x" * 64
        off_b, crc_b = store.write(2, 1, b"y" * 64)
        assert off_b == off_a + store.record_size
        # The A slot survives the B write (the checkpoint-retention
        # property the A/B discipline exists for).
        assert store.read(off_a, sid=2, want_crc=crc) == b"x" * 64
        assert store.read(off_b, sid=2, want_crc=crc_b) == b"y" * 64

    def test_torn_record_is_typed_never_bytes(self, tmp_path):
        path = str(tmp_path / "c.cold")
        store = ColdSpanStore(path, 2, 64)
        off, crc = store.write(1, 0, b"z" * 64)
        with open(path, "r+b") as f:  # scribble mid-payload
            f.seek(off + 30)
            f.write(b"\xff\xfe")
        with pytest.raises(ColdSpanCorrupt):
            store.read(off, sid=1, want_crc=crc)

    def test_never_written_slot_is_typed(self, tmp_path):
        store = ColdSpanStore(str(tmp_path / "c.cold"), 2, 64)
        with pytest.raises(ColdSpanCorrupt):
            store.read(store.offset(0, 0), sid=0)

    def test_span_id_mismatch_is_typed(self, tmp_path):
        store = ColdSpanStore(str(tmp_path / "c.cold"), 4, 64)
        off, _ = store.write(3, 0, b"q" * 64)
        with pytest.raises(ColdSpanCorrupt):
            store.read(off, sid=1)

    def test_content_drift_against_want_crc_is_typed(self, tmp_path):
        store = ColdSpanStore(str(tmp_path / "c.cold"), 2, 64)
        off, crc = store.write(0, 0, b"a" * 64)
        store.write(0, 0, b"b" * 64)  # same slot, new content
        with pytest.raises(ColdSpanCorrupt):
            store.read(off, sid=0, want_crc=crc)

    def test_typed_error_is_a_chunk_corrupt(self, tmp_path):
        # The restore fallback walk catches ChunkCorrupt — cold-span
        # failures must be that type.
        assert issubclass(ColdSpanCorrupt, ChunkCorrupt)

    def test_reopen_never_truncates(self, tmp_path):
        path = str(tmp_path / "c.cold")
        store = ColdSpanStore(path, 8, 64)
        off, crc = store.write(7, 1, b"k" * 64)
        store.close()
        small = ColdSpanStore(path, 2, 64)  # smaller layout, same file
        assert small.read(off, sid=7, want_crc=crc) == b"k" * 64


class TestTieredFrameRing:
    def _ring(self, tmp_path, cap=64, budget=0, span=4):
        return TieredFrameRing(
            cap, OBS, hot_budget_bytes=budget or 10 ** 9,
            spill_path=str(tmp_path / "r.cold"), span_frames=span,
        )

    def test_random_ops_match_dense_oracle(self, tmp_path):
        rng = np.random.default_rng(0)
        cap = 64
        ring = self._ring(tmp_path, cap=cap, budget=1)  # evict-everything
        oracle = np.zeros((cap, *OBS), np.uint8)
        for step in range(60):
            op = rng.integers(0, 3)
            if op == 0:  # scattered put
                idx = rng.choice(cap, size=rng.integers(1, 9),
                                 replace=False)
                frames = rng.integers(0, 255, (len(idx), *OBS), np.uint8)
                ring.put(idx, frames)
                oracle[idx] = frames
            elif op == 1:  # wrap-aware span put
                start = int(rng.integers(0, cap))
                n = int(rng.integers(1, 20))
                frames = rng.integers(0, 255, (n, *OBS), np.uint8)
                ring.put_span(start, n, frames)
                sl = (start + np.arange(n)) % cap
                oracle[sl] = frames
            else:
                ring.spill()  # budget=1 → everything cold
            idx = rng.choice(cap, size=8, replace=False)
            np.testing.assert_array_equal(ring.get(idx), oracle[idx])
            start = int(rng.integers(0, cap))
            n = int(rng.integers(1, 20))
            sl = (start + np.arange(n)) % cap
            np.testing.assert_array_equal(ring.get_span(start, n),
                                          oracle[sl])
        assert ring.spill_writes > 0 and ring.fault_reads > 0

    def test_never_written_reads_zeros(self, tmp_path):
        ring = self._ring(tmp_path)
        np.testing.assert_array_equal(
            ring.get(np.asarray([0, 63])), np.zeros((2, *OBS), np.uint8)
        )

    def test_eviction_is_lru_and_respects_budget(self, tmp_path):
        ring = TieredFrameRing(
            64, OBS, hot_budget_bytes=6 * 4 * int(np.prod(OBS)),
            spill_path=str(tmp_path / "r.cold"), span_frames=4,
            watermark_low=1.0,
        )
        frames = np.arange(64 * np.prod(OBS), dtype=np.uint8).reshape(
            64, *OBS)
        ring.put_span(0, 64, frames)          # 16 spans hot
        ring.get(np.asarray([0]))             # span 0 most-recent
        spilled, wrote = ring.spill()
        assert ring.hot_bytes <= ring.hot_budget_bytes
        assert spilled == 10 and wrote > 0    # 16 → 6 spans
        assert 0 in ring._hot                 # recently-sampled stayed

    def test_clean_re_eviction_writes_nothing(self, tmp_path):
        ring = self._ring(tmp_path, budget=1)
        ring.put_span(0, 8, np.ones((8, *OBS), np.uint8))
        _, wrote1 = ring.spill()
        assert wrote1 > 0
        ring.get(np.asarray([0]))             # fault back, unmodified
        _, wrote2 = ring.spill()
        assert wrote2 == 0                    # disk copy still current
        assert ring.fault_reads == 1

    def test_torn_cold_span_fault_is_typed(self, tmp_path):
        ring = self._ring(tmp_path, budget=1)
        ring.put_span(0, 4, np.full((4, *OBS), 7, np.uint8))
        ring.spill()
        off = ring.store.offset(0, int(ring._cold_ab[0]))
        with open(ring.store.path, "r+b") as f:
            f.seek(off + 20)
            f.write(b"\x00\x01\x02")
        with pytest.raises(ColdSpanCorrupt):
            ring.get(np.asarray([0]))


class TestTieredReplayParity:
    """The tier moves bytes, never the law: tiered twins are bit-exact
    with dense ones through add / sample / update / snapshot, with
    evictions forced between every operation."""

    @pytest.mark.parametrize("kind", ["dedup", "native"])
    def test_sample_update_snapshot_bit_exact(self, tmp_path, kind):
        dense, tiered = make_pair(kind, tmp_path)
        rng = np.random.default_rng(1)
        for k in range(16):  # wraps both rings
            p, c = prio(seed=k), dchunk(seq=k, seed=k)
            np.testing.assert_array_equal(dense.add(p, c), tiered.add(p, c))
            tiered.spill_cold()
        assert tiered.tier_stats()["spill_writes"] > 0
        for k in range(12):
            ra = dense.sample(16, rng=np.random.default_rng(50 + k))
            rb = tiered.sample(16, rng=np.random.default_rng(50 + k))
            np.testing.assert_array_equal(ra.indices, rb.indices)
            np.testing.assert_array_equal(ra.is_weights, rb.is_weights)
            np.testing.assert_array_equal(ra.transition.obs,
                                          rb.transition.obs)
            np.testing.assert_array_equal(ra.transition.next_obs,
                                          rb.transition.next_obs)
            up = (np.abs(rng.normal(size=16)) + 0.1).astype(np.float32)
            dense.update_priorities(ra.indices, up)
            tiered.update_priorities(rb.indices, up)
            tiered.spill_cold()
        assert tiered.tier_stats()["fault_reads"] > 0
        assert_same_state(dense.state_dict(), tiered.state_dict())

    def test_native_two_phase_equals_fused_call(self, tmp_path):
        """rc_sample_idx + rc_gather_frames (the tiered path) is
        bit-identical to the one-call rc_sample given the same uniforms —
        all-hot, so no faults perturb anything."""
        cls = _native_or_skip()
        fused = cls(128, OBS)
        two = cls(128, OBS, hot_frame_budget_bytes=10 ** 9,
                  spill_dir=str(tmp_path / "s"), spill_span_frames=4)
        for k in range(6):
            p, c = prio(seed=k), dchunk(seq=k, seed=k)
            fused.add(p, c)
            two.add(p, c)
        for k in range(8):
            u = np.random.default_rng(k).random(16)
            ra = fused._sample_with_uniforms(u.copy(), 0.4)
            rb = two._sample_with_uniforms(u.copy(), 0.4)
            np.testing.assert_array_equal(ra.indices, rb.indices)
            np.testing.assert_array_equal(ra.is_weights, rb.is_weights)
            np.testing.assert_array_equal(ra.transition.obs,
                                          rb.transition.obs)
        assert two.tier_stats()["fault_reads"] == 0

    def test_tiered_prioritized_replay_parity(self, tmp_path):
        from ape_x_dqn_tpu.replay.buffer import PrioritizedReplay
        from ape_x_dqn_tpu.types import NStepTransition

        dense = PrioritizedReplay(64, OBS)
        tiered = PrioritizedReplay(
            64, OBS, hot_frame_budget_bytes=4096,
            spill_dir=str(tmp_path / "p"), spill_span_frames=4,
        )
        rng = np.random.default_rng(2)
        for k in range(8):
            M = 16
            t = NStepTransition(
                obs=rng.integers(0, 255, (M, *OBS), np.uint8),
                action=rng.integers(0, 3, M).astype(np.int32),
                reward=rng.normal(size=M).astype(np.float32),
                discount=np.full(M, 0.9, np.float32),
                next_obs=rng.integers(0, 255, (M, *OBS), np.uint8),
            )
            p = prio(M, seed=k)
            np.testing.assert_array_equal(dense.add(p, t), tiered.add(p, t))
            tiered.spill_cold()
        for k in range(6):
            ra = dense.sample(8, rng=np.random.default_rng(k))
            rb = tiered.sample(8, rng=np.random.default_rng(k))
            np.testing.assert_array_equal(ra.indices, rb.indices)
            np.testing.assert_array_equal(ra.transition.obs,
                                          rb.transition.obs)
            np.testing.assert_array_equal(ra.transition.next_obs,
                                          rb.transition.next_obs)
        stats = tiered.tier_stats()
        assert stats["spill_writes"] > 0 and stats["fault_reads"] > 0
        assert_same_state(dense.state_dict(), tiered.state_dict())


class TestTieredCheckpoint:
    """Cold-ref bases: bytes ∝ hot budget, O(hot) adopt restore, dense ↔
    tiered cross-restores bit-exact, torn cold records typed."""

    def _build_chain(self, root, spill, kind, saves=6):
        if kind == "dedup":
            rep = DedupReplay(64, OBS, hot_frame_budget_bytes=2048,
                              spill_dir=spill, spill_span_frames=4)
        else:
            cls = _native_or_skip()
            rep = cls(64, OBS, hot_frame_budget_bytes=2048,
                      spill_dir=spill, spill_span_frames=4)
        ck = IncrementalCheckpointer(root, rep, base_every=2, sync=True)
        for k in range(saves):
            rep.add(prio(seed=k), dchunk(seq=k, seed=k))
            rep.spill_cold()
            b = rep.sample(8, rng=np.random.default_rng(k))
            rep.update_priorities(b.indices, prio(8, seed=100 + k))
            rep.spill_cold()
            ck.save(k + 1)
        return rep

    @pytest.mark.parametrize("kind", ["dedup", "native"])
    def test_base_references_cold_spans_and_adopt_restores(
            self, tmp_path, kind):
        root, spill = str(tmp_path), str(tmp_path / "spill")
        rep = self._build_chain(root, spill, kind)
        want = rep.state_dict()
        from ape_x_dqn_tpu.utils.checkpoint_inc import (
            inc_dir,
            read_chunk,
            read_manifest,
        )

        manifest = read_manifest(inc_dir(root))
        base = read_chunk(os.path.join(inc_dir(root),
                                       manifest["chunks"][0]))
        assert "tier_cold_sids" in base, "base must reference cold spans"
        assert "frames" not in base
        assert manifest["cold_ref_bytes"] > 0
        # Adopt restore: same spill dir, fresh replay → zero fault reads.
        if kind == "dedup":
            r2 = DedupReplay(64, OBS, hot_frame_budget_bytes=2048,
                             spill_dir=spill, spill_span_frames=4)
        else:
            r2 = _native_or_skip()(64, OBS, hot_frame_budget_bytes=2048,
                                   spill_dir=spill, spill_span_frames=4)
        step = load_incremental_replay(root, r2)
        assert step == manifest["step"]
        # O(hot) restore: the cold tier is adopted in place, not paged
        # in.  The only faults allowed are the delta-apply's partially
        # overwritten boundary spans (bounded by chain length, not by
        # cold size).
        stats = r2.tier_stats()
        assert stats["fault_reads"] <= 2 * (len(manifest["chunks"]) - 1)
        assert stats["fault_bytes"] < manifest["cold_ref_bytes"]
        assert_same_state(want, r2.state_dict())

    @pytest.mark.parametrize("kind", ["dedup", "native"])
    def test_cross_restore_into_dense_twin(self, tmp_path, kind):
        root, spill = str(tmp_path), str(tmp_path / "spill")
        rep = self._build_chain(root, spill, kind)
        want = rep.state_dict()
        # Tiered chain → the OTHER dense twin (numpy ↔ native stays
        # interchangeable through the tier).
        dense = (_native_or_skip()(64, OBS) if kind == "dedup"
                 else DedupReplay(64, OBS))
        step = load_incremental_replay(root, dense)
        assert step == 6
        assert_same_state(want, dense.state_dict())

    @pytest.mark.parametrize("kind", ["dedup", "native"])
    def test_heavy_churn_between_saves_keeps_refs_valid(self, tmp_path,
                                                        kind):
        """Regression (found driving the real CLI trainer): a small ring
        wrapping MANY times between saves re-spills every span repeatedly;
        without the cold_refs pin the A/B slots both get rewritten and the
        committed base's refs die.  Pinned, the chain restores bit-exactly
        however hard the ring churns."""
        root, spill = str(tmp_path), str(tmp_path / "spill")
        if kind == "dedup":
            make = lambda: DedupReplay(  # noqa: E731
                32, OBS, hot_frame_budget_bytes=512,
                spill_dir=spill, spill_span_frames=4)
        else:
            cls = _native_or_skip()
            make = lambda: cls(  # noqa: E731
                32, OBS, hot_frame_budget_bytes=512,
                spill_dir=spill, spill_span_frames=4)
        rep = make()
        ck = IncrementalCheckpointer(root, rep, base_every=8, sync=True)
        seq = 0
        for save in range(4):
            for _ in range(6):  # several full ring wraps per interval
                rep.add(prio(seed=seq), dchunk(seq=seq, seed=seq))
                rep.spill_cold()
                rep.sample(8, rng=np.random.default_rng(seq))
                rep.spill_cold()
                seq += 1
            ck.save(save + 1)
        want = rep.state_dict()
        r2 = make()
        assert load_incremental_replay(root, r2) == 4
        assert_same_state(want, r2.state_dict())

    def test_dense_chain_restores_into_tiered(self, tmp_path):
        root = str(tmp_path)
        rep = DedupReplay(64, OBS)
        ck = IncrementalCheckpointer(root, rep, base_every=2, sync=True)
        for k in range(5):
            rep.add(prio(seed=k), dchunk(seq=k, seed=k))
            ck.save(k + 1)
        want = rep.state_dict()
        r2 = DedupReplay(64, OBS, hot_frame_budget_bytes=2048,
                         spill_dir=str(tmp_path / "spill2"),
                         spill_span_frames=4)
        assert load_incremental_replay(root, r2) == 5
        assert_same_state(want, r2.state_dict())

    @pytest.mark.parametrize("kind", ["dedup", "native"])
    def test_torn_cold_record_restore_is_fallback_or_typed(
            self, tmp_path, kind):
        """The satellite contract: a torn cold span is detected by CRC and
        restore either walks back to a still-valid rung (exact state) or
        surfaces the typed error — never silently-wrong frames."""
        root, spill = str(tmp_path), str(tmp_path / "spill")
        self._build_chain(root, spill, kind)
        # Scribble EVERY record header in the spill file — all cold refs
        # in all generations break.
        path = os.path.join(spill, "frames.cold")
        with open(path, "r+b") as f:
            sz = os.fstat(f.fileno()).st_size
            for off in range(0, sz, 256):
                f.seek(off)
                f.write(b"\xde\xad")
        fresh = DedupReplay(64, OBS)
        with pytest.raises(ChunkCorrupt):
            load_incremental_replay(root, fresh)
        fresh2 = DedupReplay(64, OBS)
        try:
            step = load_incremental_replay(root, fresh2, fallback=True)
        except ChunkCorrupt:
            return  # typed all the way down — acceptable per contract
        assert step is not None  # a rung restored → it was CRC-verified


class TestTierEvictor:
    def test_background_evictor_holds_budget(self, tmp_path):
        rep = DedupReplay(128, OBS, hot_frame_budget_bytes=4096,
                          spill_dir=str(tmp_path / "s"),
                          spill_span_frames=4)
        ev = TierEvictor(rep, poll_s=0.01)
        ev.start()
        try:
            for k in range(12):
                rep.add(prio(seed=k), dchunk(seq=k, seed=k))
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if rep.tier.hot_bytes <= 4096:
                    break
                time.sleep(0.01)
            assert rep.tier.hot_bytes <= 4096
            assert ev.error is None
        finally:
            ev.stop()
        # Samples after background eviction still serve correct frames.
        dense = DedupReplay(128, OBS)
        for k in range(12):
            dense.add(prio(seed=k), dchunk(seq=k, seed=k))
        ra = dense.sample(8, rng=np.random.default_rng(9))
        rb = rep.sample(8, rng=np.random.default_rng(9))
        np.testing.assert_array_equal(ra.transition.obs, rb.transition.obs)


def _spill_victim(root: str, mode: str) -> None:
    """Kill-barrage child: ingest + spill (+ fault, in ``fault`` mode) +
    sync checkpoint saves as fast as possible until SIGKILLed."""
    spill = os.path.join(root, "spill")
    rep = DedupReplay(64, OBS, hot_frame_budget_bytes=1024,
                      spill_dir=spill, spill_span_frames=4)
    ck = IncrementalCheckpointer(root, rep, sync=True, base_every=2)
    step = 0
    while True:
        rep.add(prio(seed=step), dchunk(seq=step, seed=step))
        rep.spill_cold()
        if mode == "fault":
            # Read-heavy phase: faults pull spans back, then re-evict.
            rep.sample(8, rng=np.random.default_rng(step))
            rep.spill_cold()
        step += 1
        ck.save(step)


class TestSigkillMidSpillAndFault:
    @pytest.mark.parametrize("mode", ["spill", "fault"])
    def test_kill_leaves_detectable_records_and_restorable_chain(
            self, tmp_path, mode):
        """SIGKILL a child mid-spill / mid-fault: every record in the
        spill file must be valid-or-typed (no silent garbage), and the
        committed manifest must still restore — exactly (the expected
        state is rebuilt by replaying the deterministic feed) or via a
        typed/fallback path when the kill tore a referenced record."""
        from ape_x_dqn_tpu.utils.checkpoint_inc import (
            inc_dir,
            read_manifest,
        )

        ctx = multiprocessing.get_context("fork")
        rng = np.random.default_rng(0)
        for round_i in range(2):
            root = str(tmp_path / f"{mode}-{round_i}")
            os.makedirs(root, exist_ok=True)
            proc = ctx.Process(target=_spill_victim, args=(root, mode),
                               daemon=True)
            proc.start()
            try:
                deadline = time.monotonic() + 60.0
                while read_manifest(inc_dir(root)) is None:
                    assert proc.is_alive(), "victim died on its own"
                    assert time.monotonic() < deadline, "no commit in 60s"
                    time.sleep(0.01)
                time.sleep(float(rng.uniform(0.02, 0.2)))
            finally:
                os.kill(proc.pid, signal.SIGKILL)
                proc.join(10.0)
            # (a) Every A/B record slot: valid or typed, never silent.
            store = ColdSpanStore(
                os.path.join(root, "spill", "frames.cold"),
                n_spans=20, max_payload=4 * int(np.prod(OBS)),
            )
            seen = 0
            for sid in range(20):
                for ab in (0, 1):
                    try:
                        store.read(store.offset(sid, ab), sid=sid)
                        seen += 1
                    except ColdSpanCorrupt:
                        pass
            store.close()
            # (b) The committed chain restores (fallback may walk torn
            # cold refs back; typed if every rung is gone).
            manifest = read_manifest(inc_dir(root))
            rep = DedupReplay(64, OBS, hot_frame_budget_bytes=1024,
                              spill_dir=os.path.join(root, "spill"),
                              spill_span_frames=4)
            try:
                step = load_incremental_replay(root, rep, fallback=True)
            except ChunkCorrupt:
                continue  # typed — acceptable; next round
            assert step is not None and step >= 1
            # (c) Exact content: replay the deterministic feed to `step`
            # in a dense twin and compare (ingest-only schedule is
            # deterministic in both modes — sampling never mutates
            # frames, and priorities only restamp on update, which the
            # victim never calls).
            if mode == "spill":
                twin = DedupReplay(64, OBS)
                for k in range(step):
                    twin.add(prio(seed=k), dchunk(seq=k, seed=k))
                assert_same_state(twin.state_dict(), rep.state_dict())
            assert manifest["step"] >= step
