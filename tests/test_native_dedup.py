"""Native (C++) dedup replay core vs the numpy oracle (verdict item 1b).

n_stripes=1 must be BIT-exact with replay.dedup.DedupReplay — same slots,
same samples, IS weights to 1-ulp (libm vs numpy pow), same frame bytes — through FIFO wrap,
frame-death sweeps, restamps, and snapshot roundtrips (snapshots are
interchangeable between the two implementations).  Striped mode checks
the per-stripe sampling law and lock discipline under threads.
"""

import numpy as np
import pytest

from ape_x_dqn_tpu.replay.dedup import DedupReplay
from ape_x_dqn_tpu.replay.native_dedup import (
    NativeDedupReplay,
    native_dedup_available,
    native_dedup_error,
)
from ape_x_dqn_tpu.replay.sum_tree import SumTree
from ape_x_dqn_tpu.types import DedupChunk

pytestmark = pytest.mark.skipif(
    not native_dedup_available(),
    reason=f"native replay core unavailable: {native_dedup_error()}",
)

OBS = (5, 5, 1)


def frame(seq: int) -> np.ndarray:
    return np.full(OBS, seq % 251, np.uint8)


def make_chunk(source, chunk_seq, fbase, n_tx=6, carry=0, prev_frames=0):
    U = n_tx + 1
    frames = np.stack([frame(fbase + i) for i in range(U)])
    rng = np.random.default_rng(chunk_seq * 131 + source)
    m = n_tx + carry
    return DedupChunk(
        frames=frames,
        obs_ref=np.concatenate([
            -np.arange(carry, 0, -1, dtype=np.int32),
            np.arange(n_tx, dtype=np.int32)]),
        next_ref=np.concatenate([
            np.zeros(carry, np.int32),
            np.arange(1, n_tx + 1, dtype=np.int32)]),
        action=rng.integers(0, 4, m).astype(np.int32),
        reward=rng.normal(size=m).astype(np.float32),
        discount=np.full(m, 0.97, np.float32),
        source=source, chunk_seq=chunk_seq, prev_frames=prev_frames,
    )


def stream(n_chunks, n_tx=6, source=9):
    out, fbase, prev_U = [], 0, 0
    for i in range(n_chunks):
        c = make_chunk(source, i, fbase, n_tx=n_tx,
                       carry=2 if i else 0, prev_frames=prev_U)
        out.append(c)
        fbase += c.frames.shape[0]
        prev_U = c.frames.shape[0]
    return out


def pair(capacity=64, frame_ratio=2.0, **kw):
    nat = NativeDedupReplay(capacity, OBS, frame_ratio=frame_ratio, **kw)
    ref = DedupReplay(capacity, OBS, sum_tree_cls=SumTree,
                      frame_ratio=frame_ratio)
    return nat, ref


class TestNativeParity:
    def test_bit_exact_through_wrap(self):
        nat, ref = pair()
        prng = np.random.default_rng(0)
        for c in stream(40):
            p = (np.abs(prng.normal(size=c.action.shape[0])) + 0.1)
            i1 = nat.add(p, c)
            i2 = ref.add(p, c)
            np.testing.assert_array_equal(i1, i2)
        assert nat.size() == ref.size() == 64
        assert nat.stats == ref.stats
        assert nat.max_priority() == pytest.approx(ref.max_priority())
        for t in range(6):
            b1 = nat.sample(16, beta=0.5, rng=np.random.default_rng(t))
            b2 = ref.sample(16, beta=0.5, rng=np.random.default_rng(t))
            np.testing.assert_array_equal(b1.indices, b2.indices)
            np.testing.assert_allclose(b1.is_weights, b2.is_weights, rtol=2e-7)
            for f in ("obs", "action", "reward", "discount", "next_obs"):
                np.testing.assert_array_equal(
                    getattr(b1.transition, f), getattr(b2.transition, f), f
                )
            upd = np.abs(np.random.default_rng(50 + t).normal(size=16)) + 0.1
            nat.update_priorities(b1.indices, upd)
            ref.update_priorities(b2.indices, upd)

    def test_frame_death_and_restamp_guard_parity(self):
        nat, ref = pair(frame_ratio=0.5)
        for c in stream(30, n_tx=4):
            p = np.ones(c.action.shape[0])
            nat.add(p, c)
            ref.add(p, c)
        assert nat.stats["frame_dead"] == ref.stats["frame_dead"] > 0
        dead = np.nonzero(~ref._alive[: ref.size()])[0]
        assert dead.size
        nat.update_priorities(dead[:4], np.full(4, 7.7))
        ref.update_priorities(dead[:4], np.full(4, 7.7))
        for s in dead[:4]:
            assert float(nat._lib.rc_get_mass(nat._handle, int(s))) == 0.0
        b1 = nat.sample(16, rng=np.random.default_rng(1))
        b2 = ref.sample(16, rng=np.random.default_rng(1))
        np.testing.assert_array_equal(b1.indices, b2.indices)
        np.testing.assert_array_equal(
            b1.transition.obs, b2.transition.obs
        )

    def test_carry_gap_parity(self):
        nat, ref = pair()
        c0 = make_chunk(3, 0, 0)
        gap = make_chunk(3, 4, 7, carry=2, prev_frames=7)
        for r in (nat, ref):
            r.add(np.ones(6), c0)
            r.add(np.ones(8), gap)
        assert nat.stats["dropped_carry"] == ref.stats["dropped_carry"] == 2
        assert nat.size() == ref.size()

    def test_snapshots_interchange(self):
        """A native snapshot restores into the numpy replay and vice versa
        — one checkpoint format for the host dedup path."""
        nat, ref = pair(capacity=32, frame_ratio=1.5)
        prng = np.random.default_rng(2)
        for c in stream(20, n_tx=4):
            p = np.abs(prng.normal(size=c.action.shape[0])) + 0.1
            nat.add(p, c)
            ref.add(p, c)
        # native -> numpy
        ref2 = DedupReplay(32, OBS, sum_tree_cls=SumTree, frame_ratio=1.5)
        ref2.load_state_dict(nat.state_dict())
        b1 = ref2.sample(8, rng=np.random.default_rng(5))
        b2 = ref.sample(8, rng=np.random.default_rng(5))
        np.testing.assert_array_equal(b1.indices, b2.indices)
        np.testing.assert_array_equal(b1.transition.obs, b2.transition.obs)
        # numpy -> native
        nat2 = NativeDedupReplay(32, OBS, frame_ratio=1.5)
        nat2.load_state_dict(ref.state_dict())
        b3 = nat2.sample(8, rng=np.random.default_rng(5))
        np.testing.assert_array_equal(b3.indices, b2.indices)
        np.testing.assert_array_equal(b3.transition.obs, b2.transition.obs)
        np.testing.assert_allclose(b3.is_weights, b2.is_weights, rtol=2e-7)
        # carry continues across the restore
        nxt = stream(21, n_tx=4)[-1]
        idx = nat2.add(np.ones(6), nxt)
        assert len(idx) == 6 and nat2.stats["dropped_carry"] == 0


class TestStripedLaw:
    def test_stripes_cover_all_slots_and_weights_bounded(self):
        nat = NativeDedupReplay(64, OBS, frame_ratio=2.0, n_stripes=4)
        prng = np.random.default_rng(0)
        for c in stream(40):
            nat.add(np.abs(prng.normal(size=c.action.shape[0])) + 0.1, c)
        seen = set()
        for t in range(200):
            b = nat.sample(16, rng=np.random.default_rng(t))
            seen.update(int(i) for i in b.indices)
            assert np.all(b.is_weights > 0) and np.all(b.is_weights <= 1.0)
            # stripe quota: 4 rows per stripe per sample
            stripes = np.asarray(b.indices) % 4
            assert all((stripes == s).sum() == 4 for s in range(4))
        assert len(seen) > 55  # proportional sampling reaches ~every slot

    def test_striped_frequency_matches_realized_law(self):
        """Empirical sampling frequency ∝ (mass / stripe_total) / K — the
        documented law the IS weights correct for."""
        C, K = 16, 4
        nat = NativeDedupReplay(C, OBS, frame_ratio=4.0, n_stripes=K)
        # One chunk with known priorities: slot i gets priority i+1.
        c = make_chunk(1, 0, 0, n_tx=C)
        nat.add(np.arange(1, C + 1, dtype=np.float64), c)
        mass = np.array([
            float(nat._lib.rc_get_mass(nat._handle, s)) for s in range(C)
        ])
        stripe_tot = np.array([mass[s::K].sum() for s in range(K)])
        expect = np.array([
            mass[s] / stripe_tot[s % K] / K for s in range(C)
        ])
        counts = np.zeros(C)
        trials = 3000
        for t in range(trials):
            b = nat.sample(8, rng=np.random.default_rng(t))
            for i in b.indices:
                counts[int(i)] += 1
        freq = counts / (trials * 8)
        np.testing.assert_allclose(freq, expect, atol=0.01)

    def test_batch_not_divisible_rejected(self):
        nat = NativeDedupReplay(64, OBS, n_stripes=4)
        nat.add(np.ones(6), make_chunk(1, 0, 0))
        with pytest.raises(ValueError, match="n_stripes"):
            nat.sample(10)

    def test_threaded_adds_and_samples(self):
        import threading

        nat = NativeDedupReplay(256, OBS, frame_ratio=2.0, n_stripes=4)
        for c in stream(10):
            nat.add(np.ones(c.action.shape[0]), c)
        errs = []

        def sampler():
            try:
                for t in range(50):
                    b = nat.sample(16, rng=np.random.default_rng(t))
                    assert np.isfinite(b.is_weights).all()
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        def adder(src):
            try:
                fbase, prev = 0, 0
                for i in range(30):
                    c = make_chunk(src, i, fbase, carry=2 if i else 0,
                                   prev_frames=prev)
                    nat.add(np.ones(c.action.shape[0]), c)
                    fbase += c.frames.shape[0]
                    prev = c.frames.shape[0]
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        threads = [threading.Thread(target=sampler)] + [
            threading.Thread(target=adder, args=(100 + s,)) for s in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs


class TestStripedFanOut:
    """The parallel per-stripe fan-out (rc_sample_stripe / rc_update_stripe
    through the wrapper's persistent thread pool) — ISSUE 5 satellite: the
    BENCH_r06 'striped4 wrapper serializes calls' defect, fixed."""

    def _filled(self, n_stripes=2, capacity=256):
        nat = NativeDedupReplay(capacity, OBS, frame_ratio=2.0,
                                n_stripes=n_stripes)
        prng = np.random.default_rng(0)
        for c in stream(40):
            nat.add(np.abs(prng.normal(size=c.action.shape[0])) + 0.1, c)
        return nat

    def test_fanout_bit_parity_with_serial_rc_sample(self):
        """Same uniforms through the parallel fan-out and the serial C
        rc_sample: identical slots, bit-identical weights, same rows."""
        from ape_x_dqn_tpu.replay.native_dedup import (
            _f32p, _f64p, _i32p, _i64p, _p, _u8p,
        )

        nat = self._filled(n_stripes=4)
        B = 32
        for trial in range(5):
            u = np.ascontiguousarray(
                np.random.default_rng(trial).random(B)
            )
            got = nat._sample_with_uniforms(u.copy(), beta=0.5)
            idx = np.empty(B, np.int64)
            w = np.empty(B, np.float64)
            obs = np.empty((B, *OBS), np.uint8)
            nxt = np.empty((B, *OBS), np.uint8)
            act = np.empty(B, np.int32)
            rew = np.empty(B, np.float32)
            dis = np.empty(B, np.float32)
            rc = nat._lib.rc_sample(
                nat._handle, B, 0.5, _p(u, _f64p), _p(idx, _i64p),
                _p(w, _f64p), _p(obs, _u8p), _p(nxt, _u8p),
                _p(act, _i32p), _p(rew, _f32p), _p(dis, _f32p),
            )
            assert rc == 0
            np.testing.assert_array_equal(got.indices, idx.astype(np.int32))
            np.testing.assert_array_equal(
                got.is_weights, w.astype(np.float32)
            )
            np.testing.assert_array_equal(got.transition.obs, obs)
            np.testing.assert_array_equal(got.transition.next_obs, nxt)
            np.testing.assert_array_equal(got.transition.action, act)

    def test_update_fanout_parity_and_duplicate_last_wins(self):
        a, b = self._filled(n_stripes=4), self._filled(n_stripes=4)
        C = a.capacity
        rng = np.random.default_rng(3)
        # Duplicates across and within stripes; later entries must win.
        idx = rng.integers(0, min(C, 200), size=64).astype(np.int64)
        idx[10] = idx[40]  # forced duplicate
        prio = (np.abs(rng.normal(size=64)) + 0.05).astype(np.float32)
        a.update_priorities(idx, prio)          # parallel fan-out
        b._lib.rc_update(                        # serial C spelling
            b._handle, 64,
            idx.ctypes.data_as(
                __import__("ctypes").POINTER(__import__("ctypes").c_int64)
            ),
            prio.ctypes.data_as(
                __import__("ctypes").POINTER(__import__("ctypes").c_float)
            ),
        )
        for s in range(C):
            assert a._lib.rc_get_mass(a._handle, s) == \
                b._lib.rc_get_mass(b._handle, s)

    def test_stripe_calls_overlap_in_wall_clock(self):
        """The satellite's pin: per-stripe sample calls genuinely overlap
        — the span intervals of one fan-out intersect.  Sized so each
        stripe call does several ms of GIL-released gather work; retried
        because a 1-core host's scheduler may run short calls back-to-back
        on any single try."""
        big_obs = (48, 48, 1)
        M = 256
        nat = NativeDedupReplay(2048, big_obs, frame_ratio=2.0,
                                n_stripes=2)
        rng = np.random.default_rng(0)
        for i in range(8):
            frames = rng.integers(
                0, 255, (M + 1, *big_obs), dtype=np.uint8
            )
            nat.add(
                (np.abs(rng.normal(size=M)) + 0.1).astype(np.float32),
                DedupChunk(
                    frames=frames, source=1, chunk_seq=i,
                    obs_ref=np.arange(M, dtype=np.int32),
                    next_ref=np.arange(1, M + 1, dtype=np.int32),
                    action=rng.integers(0, 4, M).astype(np.int32),
                    reward=rng.normal(size=M).astype(np.float32),
                    discount=np.full(M, 0.97, np.float32),
                    prev_frames=M + 1,
                ),
            )
        overlapped = False
        for trial in range(15):
            nat.sample(8192, rng=np.random.default_rng(trial))
            spans = nat.last_stripe_spans
            assert len(spans) == 2
            if max(s[0] for s in spans) < min(s[1] for s in spans):
                overlapped = True
                break
        assert overlapped, (
            f"stripe calls never overlapped in 15 tries: {spans}"
        )
