"""Actor fleet tests: emission coverage, n-step alignment, priorities,
param sync (SURVEY §4 levels 1-2)."""

import numpy as np
import jax
import pytest

from ape_x_dqn_tpu.actors import ActorFleet, LocalParamSource
from ape_x_dqn_tpu.envs import ChainMDP, RandomFrameEnv
from ape_x_dqn_tpu.models.dueling import DuelingMLP
from ape_x_dqn_tpu.ops.nstep import nstep_returns_np, nstep_returns_reference


def make_fleet(num_actors=4, n_step=3, flush_every=8, **kw):
    net = DuelingMLP(num_actions=2, hidden_sizes=(16,))
    fleet = ActorFleet(
        [lambda: ChainMDP(6, time_limit=20)] * num_actors,
        net,
        n_step=n_step,
        flush_every=flush_every,
        **kw,
    )
    params = net.init(jax.random.PRNGKey(0), np.zeros((1, 6), np.uint8))
    source = LocalParamSource(params)
    fleet.sync_params(source)
    return fleet, source


def test_nstep_returns_np_matches_oracle(rng):
    rewards = rng.normal(size=(20, 3)).astype(np.float32)
    discounts = (0.99 * (rng.random((20, 3)) > 0.2)).astype(np.float32)
    got_r, got_d = nstep_returns_np(rewards, discounts, 3)
    for col in range(3):
        exp_r, exp_d = nstep_returns_reference(rewards[:, col], discounts[:, col], 3)
        np.testing.assert_allclose(got_r[:, col], exp_r, rtol=1e-5)
        np.testing.assert_allclose(got_d[:, col], exp_d, rtol=1e-5)


def test_every_step_emitted_exactly_once():
    fleet, _ = make_fleet(num_actors=2, n_step=3, flush_every=8)
    chunks, _ = fleet.collect(60)
    # Ring fills at H=11; flushes at 11, 19, 27, ... -> steps 0..7, 8..15, ...
    total = sum(c.transitions.action.shape[0] for c in chunks)
    emitted_starts = 8 * len(chunks)
    assert total == emitted_starts * 2  # × num_actors
    assert len(chunks) == (60 - 11) // 8 + 1


def test_chunk_shapes_and_dtypes():
    fleet, _ = make_fleet(num_actors=3, flush_every=4)
    chunks, _ = fleet.collect(20)
    c = chunks[0]
    m = c.transitions.action.shape[0]
    assert m == 4 * 3
    assert c.priorities.shape == (m,)
    assert c.transitions.obs.dtype == np.uint8
    assert c.transitions.reward.dtype == np.float32
    assert np.all(c.priorities >= 0)
    assert np.all(np.isfinite(c.priorities))


def test_discount_zero_at_terminals():
    # ChainMDP(2) TERMINATES (not truncates) whenever action 1 is taken from
    # the start state, so over 128 steps many emitted windows contain a true
    # MDP terminal; their bootstrap discounts must be exactly 0 (truncation
    # windows instead keep γ^(k+1) — covered by the truncation tests), and
    # none may exceed gamma^n.
    net = DuelingMLP(num_actions=2, hidden_sizes=(16,))
    fleet = ActorFleet(
        [lambda: ChainMDP(2, time_limit=20)],
        net, n_step=2, flush_every=8, gamma=0.9,
    )
    params = net.init(jax.random.PRNGKey(0), np.zeros((1, 2), np.uint8))
    fleet.sync_params(LocalParamSource(params))
    chunks, stats = fleet.collect(128)
    disc = np.concatenate([c.transitions.discount for c in chunks])
    assert np.all(disc <= 0.9**2 + 1e-6)
    assert (disc == 0.0).any(), "terminals should zero some bootstrap discounts"
    assert len(stats) > 0
    assert all(1 <= s.episode_length <= 20 for s in stats)


class _CountEnv:
    """Truncation probe with DISTINGUISHABLE observations: obs = [t]*4, so
    the episode's final observation (t == time_limit) differs from both the
    reset obs (t == 0) and every interior one — the test can see exactly
    which frame a truncated window bootstraps from."""

    def __init__(self, time_limit=5):
        self.time_limit = int(time_limit)
        self.observation_shape = (4,)
        self.num_actions = 2
        self._t = 0

    def _obs(self):
        return np.full(4, self._t, np.uint8)

    def reset(self, seed=None):
        self._t = 0
        return self._obs()

    def step(self, action):
        from ape_x_dqn_tpu.envs.core import StepResult

        self._t += 1
        return StepResult(self._obs(), 1.0, False, self._t >= self.time_limit)


def test_truncation_stores_final_obs_for_learner_bootstrap():
    """Truncated windows keep their bootstrap (envs/core.py contract), and
    it is the LEARNER's: the emitted transition carries the raw reward,
    next_obs = S_final and discount γ^(k+1), so the target net — not a
    frozen collection-time Q — values the tail on every replay."""
    gamma = 0.9
    net = DuelingMLP(num_actions=2, hidden_sizes=(16,))
    fleet = ActorFleet(
        [lambda: _CountEnv(time_limit=5)] * 2,
        net,
        n_step=1,
        flush_every=5,
        gamma=gamma,
    )
    params = net.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.uint8))
    fleet.sync_params(LocalParamSource(params))
    chunks, stats = fleet.collect(31)
    rewards = np.concatenate([c.transitions.reward for c in chunks])
    discounts = np.concatenate([c.transitions.discount for c in chunks])
    obs = np.concatenate([c.transitions.obs for c in chunks])
    next_obs = np.concatenate([c.transitions.next_obs for c in chunks])
    # Rewards are raw — never inflated by a baked-in Q bootstrap.
    np.testing.assert_allclose(rewards, 1.0, rtol=1e-6)
    # _CountEnv never terminates: every window bootstraps, discount == γ.
    np.testing.assert_allclose(discounts, gamma, rtol=1e-6)
    # Windows starting at t=4 truncate: their next_obs is the FINAL obs
    # (t=5), not the next episode's reset/first frames (t∈{0,1}).
    at_trunc = obs[:, 0] == 4
    assert at_trunc.any()
    np.testing.assert_array_equal(next_obs[at_trunc][:, 0], 5)
    # Ordinary windows chain to the in-episode successor.
    interior = obs[:, 0] < 4
    np.testing.assert_array_equal(
        next_obs[interior][:, 0], obs[interior][:, 0] + 1
    )
    # Truncated episodes still close out stats.
    assert stats and all(s.episode_length == 5 for s in stats)


def test_truncation_window_never_crosses_episodes():
    """n-step windows that span a truncation cut there: discount γ^(k+1)
    (k = offset of the boundary), return contributions past it zeroed, and
    next_obs re-targeted to the final obs — never next-episode states."""
    gamma = 0.9
    fleet = ActorFleet(
        [lambda: _CountEnv(time_limit=5)],
        DuelingMLP(num_actions=2, hidden_sizes=(8,)),
        n_step=3,
        flush_every=5,
        gamma=gamma,
    )
    net = fleet.network
    params = net.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.uint8))
    fleet.sync_params(LocalParamSource(params))
    chunks, _ = fleet.collect(40)
    disc = np.concatenate([c.transitions.discount for c in chunks])
    obs = np.concatenate([c.transitions.obs for c in chunks])
    next_obs = np.concatenate([c.transitions.next_obs for c in chunks])
    rets = np.concatenate([c.transitions.reward for c in chunks])
    # Window from t covers offsets until the boundary at t=4 (k = 4 - t for
    # t >= 2): discount γ^(k+1), next_obs = final obs (t=5), return = sum of
    # discounted +1 rewards up to the boundary.
    t0 = obs[:, 0]
    for t, k in ((2, 2), (3, 1), (4, 0)):
        m = t0 == t
        assert m.any()
        np.testing.assert_allclose(disc[m], gamma ** (k + 1), rtol=1e-6)
        np.testing.assert_array_equal(next_obs[m][:, 0], 5)
        want_ret = sum(gamma ** j for j in range(k + 1))
        np.testing.assert_allclose(rets[m], want_ret, rtol=1e-6)
    # Clean windows (start t<2) run the full horizon inside the episode.
    m = t0 < 2
    np.testing.assert_allclose(disc[m], gamma ** 3, rtol=1e-6)
    np.testing.assert_array_equal(next_obs[m][:, 0], t0[m] + 3)


def test_episode_stats_accumulate_reward():
    fleet, _ = make_fleet(num_actors=2)
    _, stats = fleet.collect(100)
    # ChainMDP pays exactly +1 on success, 0 on timeout.
    assert stats and all(s.episode_return in (0.0, 1.0) for s in stats)


def test_param_sync_poll():
    fleet, source = make_fleet(sync_every=10)
    v0 = fleet.param_version
    net = DuelingMLP(num_actions=2, hidden_sizes=(16,))
    source.publish(net.init(jax.random.PRNGKey(1), np.zeros((1, 6), np.uint8)))
    fleet.collect(10, param_source=source)
    assert fleet.param_version == v0 + 1


def test_requires_params():
    net = DuelingMLP(num_actions=2, hidden_sizes=(16,))
    fleet = ActorFleet([lambda: ChainMDP(6)], net)
    with pytest.raises(RuntimeError):
        fleet.collect(1)


class ConstObsEnv:
    """Constant observation — the greedy action is fixed, so per-actor
    deviation from it measures ε directly."""

    observation_shape = (6,)
    num_actions = 4

    def reset(self, seed=None):
        return np.full(6, 100, np.uint8)

    def step(self, action):
        from ape_x_dqn_tpu.envs import StepResult

        return StepResult(np.full(6, 100, np.uint8), 0.0, False, False)


def test_epsilon_ladder_changes_behavior():
    # Actor 0 (ε=0.9) must deviate from the greedy action far more than the
    # last actor (ε=0.9^8 ≈ 0.43... use alpha bigger) on a constant obs.
    num = 8
    net = DuelingMLP(num_actions=4, hidden_sizes=(8,))
    fleet = ActorFleet(
        [ConstObsEnv] * num,
        net,
        epsilon=0.8,
        epsilon_alpha=20.0,
        flush_every=4,
    )
    params = net.init(jax.random.PRNGKey(0), np.zeros((1, 6), np.uint8))
    fleet.sync_params(LocalParamSource(params))
    chunks, _ = fleet.collect(400)
    acts = np.concatenate(
        [c.transitions.action.reshape(-1, num) for c in chunks]
    )  # [steps, N]
    # The tiny-ε actor is near-deterministic: its modal action IS greedy.
    vals, counts = np.unique(acts[:, -1], return_counts=True)
    greedy = vals[counts.argmax()]
    deviation = (acts != greedy).mean(axis=0)
    # ε=0.8 deviates ~0.8·(3/4)=0.6 of steps; ε=0.8^21≈0.009 almost never.
    assert deviation[0] > 0.4
    assert deviation[-1] < 0.1
    assert deviation[0] > deviation[-1] + 0.3


class StepCounterEnv:
    """Obs encodes the global step index; reward at step t is t.  Never
    ends (long time limit) — a transparent probe for emission cadence."""

    observation_shape = (2,)
    num_actions = 2

    def __init__(self):
        self._c = 0

    def _obs(self):
        return np.asarray([self._c % 256, self._c // 256], np.uint8)

    def reset(self, seed=None):
        self._c = 0
        return self._obs()

    def step(self, action):
        from ape_x_dqn_tpu.envs.core import StepResult

        r = float(self._c)
        self._c += 1
        return StepResult(self._obs(), r, False, self._c >= 10_000)


def _fleet_on_counter(emission, n_step=3, flush_every=4, num_actors=2):
    net = DuelingMLP(num_actions=2, hidden_sizes=(8,))
    fleet = ActorFleet(
        [StepCounterEnv] * num_actors, net, n_step=n_step, gamma=0.5,
        flush_every=flush_every, emission=emission,
    )
    params = net.init(jax.random.PRNGKey(0), np.zeros((1, 2), np.uint8))
    fleet.sync_params(LocalParamSource(params))
    return fleet


def _window_starts(chunks):
    out = []
    for c in chunks:
        o = c.transitions.obs.astype(np.int64)
        out.append(o[:, 0] + 256 * o[:, 1])
    return np.concatenate(out) if out else np.zeros(0, np.int64)


class TestEmissionModes:
    def test_strided_reproduces_reference_window_boundaries(self):
        """actor.emission=strided must emit exactly the n-aligned window
        starts 0, n, 2n, ... with no overlap and no gaps across flush
        boundaries (the reference's advance-by-n buffer, actor.py:44-70) —
        flush_every=4 deliberately not divisible by n=3."""
        fleet = _fleet_on_counter("strided", n_step=3, flush_every=4)
        chunks, _ = fleet.collect(40)
        starts = _window_starts(chunks)
        # Both actors share the cadence; dedupe to the schedule itself.
        sched = np.unique(starts)
        want = np.arange(0, sched.max() + 1, 3)
        np.testing.assert_array_equal(sched, want)
        # Every start appears exactly once per actor (no duplicate emission).
        assert len(starts) == 2 * len(sched)
        # Return math unchanged: window at start t holds t + γ(t+1) + γ²(t+2).
        g = 0.5
        rewards = np.concatenate([c.transitions.reward for c in chunks])
        t = starts.astype(np.float64)
        np.testing.assert_allclose(
            rewards, t + g * (t + 1) + g * g * (t + 2), rtol=1e-6
        )

    def test_overlapping_emits_every_start(self):
        fleet = _fleet_on_counter("overlapping", n_step=3, flush_every=4)
        chunks, _ = fleet.collect(40)
        sched = np.unique(_window_starts(chunks))
        np.testing.assert_array_equal(sched, np.arange(sched.max() + 1))

    def test_strided_requires_flush_at_least_n(self):
        with pytest.raises(ValueError, match="flush_every >= num_steps"):
            _fleet_on_counter("strided", n_step=3, flush_every=2)

    def test_unknown_emission_rejected(self):
        with pytest.raises(ValueError, match="unknown emission"):
            _fleet_on_counter("sometimes")
