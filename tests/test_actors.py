"""Actor fleet tests: emission coverage, n-step alignment, priorities,
param sync (SURVEY §4 levels 1-2)."""

import numpy as np
import jax
import pytest

from ape_x_dqn_tpu.actors import ActorFleet, LocalParamSource
from ape_x_dqn_tpu.envs import ChainMDP, LoopEnv, RandomFrameEnv
from ape_x_dqn_tpu.models.dueling import DuelingMLP
from ape_x_dqn_tpu.ops.nstep import nstep_returns_np, nstep_returns_reference


def make_fleet(num_actors=4, n_step=3, flush_every=8, **kw):
    net = DuelingMLP(num_actions=2, hidden_sizes=(16,))
    fleet = ActorFleet(
        [lambda: ChainMDP(6, time_limit=20)] * num_actors,
        net,
        n_step=n_step,
        flush_every=flush_every,
        **kw,
    )
    params = net.init(jax.random.PRNGKey(0), np.zeros((1, 6), np.uint8))
    source = LocalParamSource(params)
    fleet.sync_params(source)
    return fleet, source


def test_nstep_returns_np_matches_oracle(rng):
    rewards = rng.normal(size=(20, 3)).astype(np.float32)
    discounts = (0.99 * (rng.random((20, 3)) > 0.2)).astype(np.float32)
    got_r, got_d = nstep_returns_np(rewards, discounts, 3)
    for col in range(3):
        exp_r, exp_d = nstep_returns_reference(rewards[:, col], discounts[:, col], 3)
        np.testing.assert_allclose(got_r[:, col], exp_r, rtol=1e-5)
        np.testing.assert_allclose(got_d[:, col], exp_d, rtol=1e-5)


def test_every_step_emitted_exactly_once():
    fleet, _ = make_fleet(num_actors=2, n_step=3, flush_every=8)
    chunks, _ = fleet.collect(60)
    # Ring fills at H=11; flushes at 11, 19, 27, ... -> steps 0..7, 8..15, ...
    total = sum(c.transitions.action.shape[0] for c in chunks)
    emitted_starts = 8 * len(chunks)
    assert total == emitted_starts * 2  # × num_actors
    assert len(chunks) == (60 - 11) // 8 + 1


def test_chunk_shapes_and_dtypes():
    fleet, _ = make_fleet(num_actors=3, flush_every=4)
    chunks, _ = fleet.collect(20)
    c = chunks[0]
    m = c.transitions.action.shape[0]
    assert m == 4 * 3
    assert c.priorities.shape == (m,)
    assert c.transitions.obs.dtype == np.uint8
    assert c.transitions.reward.dtype == np.float32
    assert np.all(c.priorities >= 0)
    assert np.all(np.isfinite(c.priorities))


def test_discount_zero_at_terminals():
    # ChainMDP(6, time_limit=20) ends episodes every <=20 steps, so over 128
    # steps many emitted windows contain an episode boundary; their bootstrap
    # discounts must be exactly 0, and none may exceed gamma^n.
    fleet, _ = make_fleet(num_actors=1, n_step=2, flush_every=8, gamma=0.9)
    chunks, stats = fleet.collect(128)
    disc = np.concatenate([c.transitions.discount for c in chunks])
    assert np.all(disc <= 0.9**2 + 1e-6)
    assert (disc == 0.0).any(), "terminals should zero some bootstrap discounts"
    assert len(stats) > 0
    assert all(1 <= s.episode_length <= 20 for s in stats)


def test_truncation_bootstrap_folds_q_into_reward():
    """Truncated steps keep their bootstrap (envs/core.py contract): the
    emitted reward at a truncation step must be r + γ·max_a Q(S_final) and
    its discount 0, while ordinary steps carry the raw reward and γ."""
    gamma = 0.9
    net = DuelingMLP(num_actions=2, hidden_sizes=(16,))
    fleet = ActorFleet(
        [lambda: LoopEnv(time_limit=5)] * 2,
        net,
        n_step=1,
        flush_every=5,
        gamma=gamma,
    )
    params = net.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.uint8))
    fleet.sync_params(LocalParamSource(params))
    chunks, stats = fleet.collect(31)
    rewards = np.concatenate([c.transitions.reward for c in chunks])
    discounts = np.concatenate([c.transitions.discount for c in chunks])
    qmax = float(
        np.asarray(net.apply(params, np.full((1, 4), 255, np.uint8))[2]).max()
    )
    trunc = discounts == 0.0
    assert trunc.any() and (~trunc).any()
    np.testing.assert_allclose(rewards[~trunc], 1.0, rtol=1e-6)
    np.testing.assert_allclose(rewards[trunc], 1.0 + gamma * qmax, rtol=1e-5)
    # Truncated episodes still close out stats.
    assert stats and all(s.episode_length == 5 for s in stats)


def test_truncation_window_never_crosses_episodes():
    """n-step windows that span a truncation must cut there (discount 0) —
    the bootstrap is inside the reward, never from next-episode states."""
    fleet = ActorFleet(
        [lambda: LoopEnv(time_limit=5)],
        DuelingMLP(num_actions=2, hidden_sizes=(8,)),
        n_step=3,
        flush_every=5,
        gamma=0.9,
    )
    net = fleet.network
    params = net.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.uint8))
    fleet.sync_params(LocalParamSource(params))
    chunks, _ = fleet.collect(40)
    disc = np.concatenate([c.transitions.discount for c in chunks])
    # Every window either runs n clean steps (γ^n) or hits the boundary (0).
    uniq = np.unique(disc)
    assert np.isclose(uniq[:, None], [0.0, 0.9**3], atol=1e-6).any(axis=1).all(), uniq
    assert (disc == 0.0).any() and (disc > 0).any()


def test_episode_stats_accumulate_reward():
    fleet, _ = make_fleet(num_actors=2)
    _, stats = fleet.collect(100)
    # ChainMDP pays exactly +1 on success, 0 on timeout.
    assert stats and all(s.episode_return in (0.0, 1.0) for s in stats)


def test_param_sync_poll():
    fleet, source = make_fleet(sync_every=10)
    v0 = fleet.param_version
    net = DuelingMLP(num_actions=2, hidden_sizes=(16,))
    source.publish(net.init(jax.random.PRNGKey(1), np.zeros((1, 6), np.uint8)))
    fleet.collect(10, param_source=source)
    assert fleet.param_version == v0 + 1


def test_requires_params():
    net = DuelingMLP(num_actions=2, hidden_sizes=(16,))
    fleet = ActorFleet([lambda: ChainMDP(6)], net)
    with pytest.raises(RuntimeError):
        fleet.collect(1)


class ConstObsEnv:
    """Constant observation — the greedy action is fixed, so per-actor
    deviation from it measures ε directly."""

    observation_shape = (6,)
    num_actions = 4

    def reset(self, seed=None):
        return np.full(6, 100, np.uint8)

    def step(self, action):
        from ape_x_dqn_tpu.envs import StepResult

        return StepResult(np.full(6, 100, np.uint8), 0.0, False, False)


def test_epsilon_ladder_changes_behavior():
    # Actor 0 (ε=0.9) must deviate from the greedy action far more than the
    # last actor (ε=0.9^8 ≈ 0.43... use alpha bigger) on a constant obs.
    num = 8
    net = DuelingMLP(num_actions=4, hidden_sizes=(8,))
    fleet = ActorFleet(
        [ConstObsEnv] * num,
        net,
        epsilon=0.8,
        epsilon_alpha=20.0,
        flush_every=4,
    )
    params = net.init(jax.random.PRNGKey(0), np.zeros((1, 6), np.uint8))
    fleet.sync_params(LocalParamSource(params))
    chunks, _ = fleet.collect(400)
    acts = np.concatenate(
        [c.transitions.action.reshape(-1, num) for c in chunks]
    )  # [steps, N]
    # The tiny-ε actor is near-deterministic: its modal action IS greedy.
    vals, counts = np.unique(acts[:, -1], return_counts=True)
    greedy = vals[counts.argmax()]
    deviation = (acts != greedy).mean(axis=0)
    # ε=0.8 deviates ~0.8·(3/4)=0.6 of steps; ε=0.8^21≈0.009 almost never.
    assert deviation[0] > 0.4
    assert deviation[-1] < 0.1
    assert deviation[0] > deviation[-1] + 0.3
