"""Fixture shm sites: a raw create (bad) and an attach (fine)."""

from multiprocessing import shared_memory


def make():
    return shared_memory.SharedMemory(create=True, size=64)   # line 7: bad


def attach(name):
    return shared_memory.SharedMemory(name=name)              # attach: fine
