"""Fixture handlers: bare except + unjustified silent swallow are
findings; the justified and the narrow variants are not."""


def decode(buf):
    try:
        return buf.decode()
    except:                     # line 8: bare except
        return None


def cleanup(sock):
    try:
        sock.close()
    except Exception:           # line 15: silent swallow, no reason
        pass


def justified(sock):
    try:
        sock.close()
    except Exception:  # noqa: BLE001 — best-effort close on teardown
        pass


def narrow(sock):
    try:
        sock.close()
    except OSError:
        pass
