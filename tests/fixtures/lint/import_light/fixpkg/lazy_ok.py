"""The blessed escape hatch: function-scope imports are lazy and legal."""


def fine():
    import jax  # function scope — never runs at import time

    return jax
