"""Contracted-light entry module: imports no heavy lib itself, but its
transitive module-scope import chain smuggles jax in via middle.py."""

from fixpkg.middle import helper  # noqa: F401
