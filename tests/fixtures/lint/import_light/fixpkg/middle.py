"""The smuggler: a module-scope jax import two hops from the entry."""

import jax  # line 3: the violation the import-light walk must find


def helper():
    return jax
