"""Fixture metrics registrations: one documented, two not."""


def setup(reg, pipe):
    reg.counter("good/counter")                       # documented: fine
    reg.gauge("bad/undocumented_gauge")               # line 6: finding
    pipe.register_jsonl_section("ghost_section", dict)  # line 7: finding
