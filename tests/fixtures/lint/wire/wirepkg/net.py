"""Fixture wire registry: F_B duplicates F_A's value; F_C is dead."""

F_A = 1
F_B = 1            # line 4: duplicate kind value
F_C = 2            # declared but never referenced anywhere -> dead kind
MAGIC_ONE = b"TSTA"
