"""Fixture decode site: every wire-registry violation in one file."""

F_D = 9              # line 3: kind declared outside the registry
MAGIC_TWO = b"TSTA"  # line 4: duplicate magic value


def decode(kind, payload):
    # line 8+: dispatches on a registered kind with NO rejection path
    if kind == F_A:  # noqa: F821 — fixture is parsed, never imported
        return payload
    return None


def route(frame_kind):
    if frame_kind == 2:  # line 15: raw literal collides with F_C's value
        return True
    return False
