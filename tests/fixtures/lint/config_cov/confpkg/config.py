"""Fixture config module: ApexConfig with one section, one undocumented
knob (ghost_target is declared but the fixture doc never mentions it)."""

import dataclasses


@dataclasses.dataclass
class ActorConfig:
    num_actors: int = 5
    documented_knob: int = 1
    ghost_target: int = 0     # line 11: declared, never documented


@dataclasses.dataclass
class ApexConfig:
    actor: ActorConfig = dataclasses.field(default_factory=ActorConfig)
