"""Fixture reader: one good read, one ghost attribute, one ghost getattr."""


def use(cfg):
    a = cfg.actor.num_actors            # declared: fine
    b = cfg.actor.ghost_knob            # line 6: ghost knob
    c = getattr(cfg.actor, "ghost_via_getattr", 0)   # line 7: ghost knob
    return a, b, c
