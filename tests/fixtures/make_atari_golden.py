"""Regenerate atari_golden.npz — the ObsPreprocess golden fixture.

Inputs are deterministic synthetic RGB frames (gradients + blocks, no RNG);
expected outputs are pinned from the cv2 luminance + INTER_AREA path at
generation time.  The fixture exists to catch silent behavior drift (cv2
version changes, preprocessing edits); regenerate ONLY on an intended
preprocessing change:

    python tests/fixtures/make_atari_golden.py
"""

import os

import numpy as np


def make_frames():
    frames = []
    # Diagonal gradient (full 210x160 ALE geometry).
    r = (np.arange(210)[:, None] + np.zeros((1, 160))) % 256
    g = (np.zeros((210, 1)) + np.arange(160)[None, :]) % 256
    b = (np.arange(210)[:, None] + np.arange(160)[None, :]) % 256
    frames.append(np.stack([r, g, b], axis=-1).astype(np.uint8))
    # Blocks + bright sprite on dark background.
    f = np.zeros((210, 160, 3), np.uint8)
    f[20:60, 30:90] = (200, 30, 120)
    f[100:116, 40:56] = 255
    f[150:, :, 1] = 90
    frames.append(f)
    return frames


def main():
    from ape_x_dqn_tpu.envs.core import StepResult  # noqa: F401 (import check)
    from ape_x_dqn_tpu.envs.atari import ObsPreprocess

    class _One:
        observation_shape = (210, 160, 3)
        num_actions = 1

        def __init__(self, frame):
            self._frame = frame

        def reset(self, seed=None):
            return self._frame

        def step(self, action):
            raise NotImplementedError

    frames = make_frames()
    outs = [
        ObsPreprocess(_One(f), 84, 84).reset() for f in frames
    ]
    path = os.path.join(os.path.dirname(__file__), "atari_golden.npz")
    np.savez_compressed(
        path,
        **{f"in_{i}": f for i, f in enumerate(frames)},
        **{f"out_{i}": o for i, o in enumerate(outs)},
    )
    print(f"wrote {path}: {len(frames)} frame pairs")


if __name__ == "__main__":
    main()
