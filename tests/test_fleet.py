"""Fleet discovery plane: the membership registry's adversarial wire
matrix + the elastic-replay routing contracts (fleet/registry.py,
replay/service.py adopt_membership, obs/fleet.py membership adoption,
autopilot's replay fleet).

The announce channel inherits the repo's decode discipline — a torn,
bitflipped, wrong-token, or stale-incarnation frame is COUNTED and never
mutates membership — and adds the lease semantics on top: joins are
versioned, leaves are immediate, silence past ``fleet.ttl_s`` is swept
with a typed ``member_lost``.  The digest-gated endpoints-file re-read
(the mtime-granularity regression) is pinned here for BOTH readers.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time

import numpy as np
import pytest

from ape_x_dqn_tpu.fleet.registry import (
    FleetAnnouncer,
    FleetClient,
    FleetRegistry,
    member_doc,
    member_id_for,
)
from ape_x_dqn_tpu.runtime.net import (
    F_FANN,
    FLEET_ACK,
    FLEET_ACK_MAGIC,
    FLEET_HELLO,
    FLEET_HELLO_VERSION,
    FLEET_MAGIC,
    frame_bytes,
)

TOKEN = 4242


def _wait(cond, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture
def registry():
    events = []
    lock = threading.Lock()

    def on_event(name, **fields):
        with lock:
            events.append((name, fields))

    reg = FleetRegistry(token=TOKEN, ttl_s=0.5,
                        on_event=on_event).serve()
    reg.test_events = events
    yield reg
    reg.close()


def _hello_bytes(token=TOKEN, version=FLEET_HELLO_VERSION,
                 magic=FLEET_MAGIC, member_id=7, incarnation=1):
    return FLEET_HELLO.pack(magic, version, member_id, incarnation, token)


def _raw_conn(reg, **hello_kw):
    """Dial + hello; returns the socket past the ack, or None when the
    registry rejected by close."""
    s = socket.create_connection(("127.0.0.1", reg.port), timeout=5.0)
    s.settimeout(5.0)
    s.sendall(_hello_bytes(**hello_kw))
    ack = b""
    while len(ack) < FLEET_ACK.size:
        try:
            got = s.recv(FLEET_ACK.size - len(ack))
        except (ConnectionError, socket.timeout):
            got = b""
        if not got:
            s.close()
            return None
        ack += got
    assert FLEET_ACK.unpack(ack)[0] == FLEET_ACK_MAGIC
    return s


def _announce_bytes(op="join", member=None, seq=1):
    body = json.dumps({"op": op, "member": member}).encode()
    return frame_bytes(F_FANN, seq, (body,))


class TestAnnounceWireAdversarial:
    """Garbage on the announce plane is counted and NEVER a membership
    mutation — the torn-ring contract, on the fourth protocol."""

    def test_wrong_token_hello_rejected_by_close(self, registry):
        assert _raw_conn(registry, token=TOKEN + 1) is None
        _wait(lambda: registry.stats()["bad_hellos"] >= 1,
              msg="bad_hellos")
        assert registry.stats()["members"] == 0

    def test_wrong_magic_and_version_rejected(self, registry):
        assert _raw_conn(registry, magic=b"NOPE") is None
        assert _raw_conn(registry, version=FLEET_HELLO_VERSION + 9) is None
        _wait(lambda: registry.stats()["bad_hellos"] >= 2,
              msg="bad_hellos")
        assert registry.stats()["accepted"] == 0

    def test_torn_frame_counted_never_applied(self, registry):
        s = _raw_conn(registry)
        doc = member_doc("replay/shard9", "replay_shard", port=1, capacity=4)
        frame = _announce_bytes(member=doc)
        s.sendall(frame[: len(frame) - 3])   # truncated mid-frame
        s.close()
        _wait(lambda: registry.stats()["torn_frames"] >= 1,
              msg="torn_frames")
        assert registry.stats()["members"] == 0
        assert registry.stats()["joins"] == 0

    def test_bitflipped_frame_torn(self, registry):
        s = _raw_conn(registry)
        frame = bytearray(_announce_bytes(
            member=member_doc("x", "observer")))
        frame[-1] ^= 0x40                    # payload bit under the crc
        s.sendall(bytes(frame))
        _wait(lambda: registry.stats()["torn_frames"] >= 1,
              msg="torn_frames")
        assert registry.stats()["members"] == 0
        s.close()

    def test_unknown_kind_counted_and_retired(self, registry):
        s = _raw_conn(registry)
        s.sendall(frame_bytes(F_FANN + 1, 1, (b"{}",)))
        _wait(lambda: registry.stats()["unexpected_kinds"] >= 1,
              msg="unexpected_kinds")
        assert registry.stats()["members"] == 0
        s.close()

    def test_well_framed_garbage_announce_counted(self, registry):
        for body in (b"not json", b'{"op": "invade"}',
                     b'{"op": "join"}'):        # join without a member
            s = _raw_conn(registry)
            s.sendall(frame_bytes(F_FANN, 1, (body,)))
            s.close()
        _wait(lambda: registry.stats()["bad_announces"] >= 3,
              msg="bad_announces")
        assert registry.stats()["members"] == 0

    def test_stale_incarnation_announce_refused(self, registry):
        cli = FleetClient("127.0.0.1", registry.port, token=TOKEN)
        fresh = member_doc("replay/shard0", "replay_shard",
                           port=9001, incarnation=3)
        cli.announce("join", fresh)
        stale = member_doc("replay/shard0", "replay_shard",
                           port=6666, incarnation=2)
        snap = cli.announce("heartbeat", stale)
        cli.close()
        assert registry.stats()["stale_rejects"] == 1
        member = snap["members"]["replay/shard0"]
        assert member["incarnation"] == 3
        assert member["port"] == 9001       # the stale doc never landed


class TestMembershipLifecycle:
    def test_join_heartbeat_leave_versions(self, registry):
        cli = FleetClient("127.0.0.1", registry.port, token=TOKEN,
                          member_id=member_id_for("w"))
        doc = member_doc("worker/host0", "worker_host",
                         varz_url="http://x/varz")
        snap = cli.announce("join", doc)
        v_join = snap["version"]
        assert snap["members"]["worker/host0"]["kind"] == "worker_host"
        # An unchanged heartbeat refreshes the lease without a version
        # bump; watchers stay cheap.
        snap = cli.announce("heartbeat", doc)
        assert snap["version"] == v_join
        snap = cli.announce("leave", doc)
        assert "worker/host0" not in snap["members"]
        assert snap["version"] > v_join
        cli.close()
        names = [n for n, _f in registry.test_events]
        assert "member_join" in names and "member_lost" in names
        lost = [f for n, f in registry.test_events if n == "member_lost"]
        assert lost[0]["reason"] == "leave"

    def test_ttl_sweep_expires_silent_member(self, registry):
        cli = FleetClient("127.0.0.1", registry.port, token=TOKEN)
        cli.announce("join", member_doc("serving/replica0",
                                        "serving_replica", port=8080))
        cli.close()
        _wait(lambda: registry.stats()["members"] == 0, timeout=5.0,
              msg="ttl expiry")
        assert registry.stats()["expired"] == 1
        lost = [f for n, f in registry.test_events if n == "member_lost"]
        assert lost and lost[-1]["reason"] == "ttl"

    def test_sweep_is_deterministic_under_explicit_now(self):
        reg = FleetRegistry(token=1, ttl_s=5.0)     # never served: no clock
        reg._apply("join", member_doc("a", "observer"))
        assert reg.sweep(time.monotonic() + 4.0) == []
        assert reg.sweep(time.monotonic() + 6.0) == ["a"]
        assert reg.stats()["members"] == 0

    def test_sync_is_a_pure_read(self, registry):
        cli = FleetClient("127.0.0.1", registry.port, token=TOKEN)
        snap = cli.sync()
        assert snap["token"] == TOKEN and snap["members"] == {}
        assert registry.stats()["joins"] == 0
        cli.close()

    def test_announcer_lifecycle_and_watch(self, registry):
        seen = []
        ann = FleetAnnouncer("127.0.0.1", registry.port, token=TOKEN,
                             member_id=member_id_for("fleet"),
                             heartbeat_s=0.05,
                             on_membership=seen.append).start()
        ann.set_member(member_doc("replay/shard0", "replay_shard",
                                  port=7001, capacity=64, incarnation=1))
        ann.poke()
        _wait(lambda: registry.members("replay_shard"), msg="join")
        ann.remove_member("replay/shard0")
        ann.poke()
        _wait(lambda: not registry.members("replay_shard"), msg="leave")
        ann.close(leave=True)
        assert seen and any("replay/shard0" in s.get("members", {})
                            for s in seen)


class TestEndpointsDigestRegression:
    """Two atomic rewrites inside one mtime granule must BOTH land: the
    re-read gates on content digest, never mtime equality.  Pinned for
    both readers (the replay client's probe refresh and the aggregator's
    endpoints-file watch)."""

    def _write(self, path, port, mtime=None):
        doc = {"token": 5, "codec": "off", "total_capacity": 64,
               "shards": [{"id": 0, "host": "127.0.0.1", "port": port,
                           "base": 0, "capacity": 64, "incarnation": 2}]}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        if mtime is not None:
            os.utime(path, (mtime, mtime))

    def test_client_refresh_survives_same_mtime_rewrite(self, tmp_path):
        from ape_x_dqn_tpu.replay.service import ShardedReplayClient

        path = str(tmp_path / "endpoints.json")
        self._write(path, port=1111, mtime=1000.0)
        client = ShardedReplayClient(
            [{"id": 0, "host": "127.0.0.1", "port": 1111, "base": 0,
              "capacity": 64, "incarnation": 2}],
            token=5, endpoints_path=path, probe_interval_s=60.0,
        )
        try:
            client._refresh_endpoints()
            assert client._clients[0].port == 1111
            # The respawn-storm rewrite: new port, SAME mtime.
            self._write(path, port=2222, mtime=1000.0)
            client._refresh_endpoints()
            assert client._clients[0].port == 2222
        finally:
            client.close()

    def test_aggregator_refresh_survives_same_mtime_rewrite(self, tmp_path):
        from ape_x_dqn_tpu.obs.fleet import FleetAggregator

        path = str(tmp_path / "endpoints.json")
        self._write(path, port=1111, mtime=1000.0)
        agg = FleetAggregator(scrape_interval_s=60.0)
        agg.watch_replay_endpoints(path)
        assert agg._eps["replay_shard0"].shard_spec["port"] == 1111
        self._write(path, port=2222, mtime=1000.0)
        agg._refresh_replay_files()
        assert agg._eps["replay_shard0"].shard_spec["port"] == 2222


class TestClientMembershipAdoption:
    """adopt_membership drives the ELASTIC routing set: admit grown
    shards, stop routing adds at draining ones, retire removed ones
    (parked write-backs dropped and counted, never raised)."""

    def _spec(self, sid, port, draining=False, incarnation=1):
        return member_doc(f"replay/shard{sid}", "replay_shard",
                          host="127.0.0.1", port=port,
                          incarnation=incarnation, base=sid * 64,
                          capacity=64, draining=draining)

    def _snapshot(self, *docs, version=1):
        return {"token": 5, "version": version, "incarnation": 1,
                "members": {d["name"]: d for d in docs}}

    def _client(self):
        from ape_x_dqn_tpu.replay.service import ShardedReplayClient

        return ShardedReplayClient(
            [{"id": 0, "host": "127.0.0.1", "port": 1111, "base": 0,
              "capacity": 64, "incarnation": 1},
             {"id": 1, "host": "127.0.0.1", "port": 1112, "base": 64,
              "capacity": 64, "incarnation": 1}],
            token=5, probe_interval_s=60.0,
        )

    def test_grow_admits_new_shard(self):
        client = self._client()
        try:
            client.adopt_membership(self._snapshot(
                self._spec(0, 1111), self._spec(1, 1112),
                self._spec(2, 1113), version=3))
            assert client.num_shards == 3
            assert client.capacity == 3 * 64
            assert sorted(client._clients) == [0, 1, 2]
            assert client.membership_version == 3
            assert client._addable() == [0, 1, 2]
        finally:
            client.close()

    def test_draining_shard_leaves_the_add_path(self):
        client = self._client()
        try:
            client.adopt_membership(self._snapshot(
                self._spec(0, 1111), self._spec(1, 1112, draining=True)))
            assert client.num_shards == 2       # still sampled/updated
            assert client._addable() == [0]
            assert client.stats()["shards_draining"] == [1]
        finally:
            client.close()

    def test_retired_shard_drops_parked_writebacks_counted(self):
        client = self._client()
        try:
            with client._state:
                client._pending[1] = {70: 0.5, 71: 0.25}
            client.adopt_membership(self._snapshot(self._spec(0, 1111)))
            assert client.num_shards == 1
            assert 1 not in client._clients
            assert client.updates_dropped == 2
            # The vacated slot range's write-backs never raise.
            client.update_priorities(np.array([70], np.int64),
                                     np.array([0.9], np.float64))
            assert client.updates_dropped == 3
        finally:
            client.close()

    def test_empty_snapshot_never_strands_the_client(self):
        client = self._client()
        try:
            client.adopt_membership({"version": 9, "members": {}})
            assert client.num_shards == 2       # routing set intact
        finally:
            client.close()


class TestAggregatorMembershipAdoption:
    def _snapshot(self, members, version=1):
        return {"token": 5, "version": version, "incarnation": 1,
                "members": {d["name"]: d for d in members}}

    def test_members_become_endpoints_and_departures_drop(self):
        from ape_x_dqn_tpu.obs.fleet import FleetAggregator

        agg = FleetAggregator(scrape_interval_s=60.0)
        shard = member_doc("replay/shard0", "replay_shard",
                           host="127.0.0.1", port=7001, base=0,
                           capacity=64, incarnation=1)
        replica = member_doc("serving/replica0", "serving_replica",
                             port=8001, varz_url="http://127.0.0.1:1/varz")
        agg.adopt_membership(self._snapshot([shard, replica], version=2))
        assert agg._eps["replay_shard0"].shard_spec["port"] == 7001
        assert agg._eps["serving/replica0"].kind == "replica"
        mem = agg._membership
        assert mem["version"] == 2 and mem["members"] == 2
        assert mem["by_kind"] == {"replay_shard": 1, "serving_replica": 1}
        # The replica leaves (retired / TTL): its endpoint must drop so
        # a departed member never reads as a liveness breach.
        agg.adopt_membership(self._snapshot([shard], version=3))
        assert "serving/replica0" not in agg._eps
        assert "replay_shard0" in agg._eps

    def test_draining_surfaced_in_membership_rollup(self):
        from ape_x_dqn_tpu.obs.fleet import FleetAggregator

        agg = FleetAggregator(scrape_interval_s=60.0)
        shard = member_doc("replay/shard1", "replay_shard",
                           host="127.0.0.1", port=7002, base=64,
                           capacity=64, draining=True)
        agg.adopt_membership(self._snapshot([shard]))
        assert agg._membership["draining"] == ["replay/shard1"]

    def test_bind_registry_adopts_in_process(self):
        from ape_x_dqn_tpu.obs.fleet import FleetAggregator

        reg = FleetRegistry(token=11, ttl_s=60.0)
        reg._apply("join", member_doc("replay/shard0", "replay_shard",
                                      host="127.0.0.1", port=7003,
                                      capacity=64))
        agg = FleetAggregator(scrape_interval_s=60.0)
        agg.bind_registry(reg)
        assert agg._eps["replay_shard0"].shard_spec["token"] == 11
        rollup = agg.scrape_once(now=time.monotonic())
        assert rollup["membership"]["members"] == 1


class _FakeReplayFleet:
    """ReplayServiceFleet's actuator surface, decoupled from processes."""

    def __init__(self, shards=2):
        self.num_shards = shards
        self.grown = 0
        self.retired = 0
        self._resharding = False

    def resharding(self):
        return self._resharding

    def grow(self, timeout=60.0):
        sid = self.num_shards
        self.num_shards += 1
        self.grown += 1
        return sid

    def retire(self, drain_grace_s=0.5, timeout=60.0):
        if self.num_shards <= 1:
            return None
        self.num_shards -= 1
        self.retired += 1
        return self.num_shards


class TestReplayFleetControl:
    def _cfg(self, **kw):
        from ape_x_dqn_tpu.config import AutopilotConfig

        kw.setdefault("enabled", True)
        kw.setdefault("cooldown_up_s", 0.0)
        kw.setdefault("cooldown_down_s", 0.0)
        kw.setdefault("hold_opposite_s", 0.0)
        kw.setdefault("replay_min_shards", 1)
        kw.setdefault("replay_max_shards", 3)
        return AutopilotConfig(**kw)

    def _controller(self, cfg, rollup=None):
        from ape_x_dqn_tpu.autopilot import (
            AutopilotController,
            ReplayFleetActuator,
        )

        fleet = _FakeReplayFleet()
        ctl = AutopilotController(cfg, rollup_fn=lambda: rollup or {})
        ctl.attach_replay(ReplayFleetActuator(fleet))
        return ctl, fleet

    def test_add_qps_breach_grows_the_fleet(self):
        ctl, fleet = self._controller(self._cfg())
        ctl.on_slo_event("slo_breach", rule="replay_add_qps", value=900.0)
        acted = ctl.step(now=100.0)
        assert [a["action"] for a in acted] == ["scale_up"]
        assert acted[0]["fleet"] == "replay"
        assert fleet.num_shards == 3

    def test_grow_respects_max_and_busy(self):
        ctl, fleet = self._controller(self._cfg(replay_max_shards=2))
        ctl.on_slo_event("slo_breach", rule="replay_add_qps", value=900.0)
        assert ctl.step(now=100.0) == []
        assert ctl.suppressed.get("replay:up:at_max") == 1
        fleet.num_shards = 1
        fleet._resharding = True            # mid-handoff: hands off
        assert ctl.step(now=101.0) == []
        assert ctl.suppressed.get("replay:up:busy") == 1
        assert fleet.grown == 0

    def test_idle_rule_retires_through_own_burn_window(self):
        cfg = self._cfg(replay_idle_add_qps_per_shard=5.0,
                        idle_window_s=10.0)
        rollup = {"replay": {"shards_alive": 2, "add_qps": 0.5}}
        ctl, fleet = self._controller(cfg, rollup=rollup)
        acted = []
        for k in range(8):                  # burn window must fill first
            acted += ctl.step(now=100.0 + k)
        assert [a["action"] for a in acted] == ["scale_down"]
        assert acted[0]["rule"] == "replay_idle"
        assert fleet.retired == 1 and fleet.num_shards == 1
        # At the floor the idle rule is suppressed, not actuated.
        for k in range(4):
            acted2 = ctl.step(now=110.0 + k)
            assert acted2 == []
        assert ctl.suppressed.get("replay:down:at_min", 0) >= 1

    def test_breach_vetoes_idle_scale_down(self):
        cfg = self._cfg(replay_idle_add_qps_per_shard=5.0,
                        idle_window_s=10.0)
        rollup = {"replay": {"shards_alive": 2, "add_qps": 0.5}}
        ctl, fleet = self._controller(cfg, rollup=rollup)
        ctl.on_slo_event("slo_breach", rule="replay_add_qps", value=900.0)
        for k in range(8):
            for a in ctl.step(now=100.0 + k):
                assert a["action"] != "scale_down"
        assert fleet.retired == 0


class TestSpillBackedShardBitExact:
    """replay.service_hot_frame_budget_bytes: a shard hosting its replay
    on the tiered (spill-backed) store answers sample/digest bit-exactly
    against an untiered twin fed the identical stream."""

    def test_tiered_shard_digest_matches_dense_twin(self, tmp_path):
        from ape_x_dqn_tpu.replay.buffer import PrioritizedReplay
        from ape_x_dqn_tpu.replay.service import (
            ReplayShardServer,
            ShardClient,
            encode_body,
        )
        from ape_x_dqn_tpu.runtime.net import CODEC_ZLIB

        obs = (6,)
        dense = PrioritizedReplay(64, obs, priority_exponent=0.6)
        tiered = PrioritizedReplay(
            64, obs, priority_exponent=0.6,
            hot_frame_budget_bytes=8 * int(np.prod(obs)),   # forces spill
            spill_dir=str(tmp_path / "spill"),
        )
        servers = [ReplayShardServer(rep, 0, incarnation=1, token=9,
                                     codec="zlib").start()
                   for rep in (dense, tiered)]
        try:
            r = np.random.default_rng(3)
            for chunk in range(6):
                n = 16
                o = r.integers(0, 255, (n, *obs), dtype=np.uint8)
                body = encode_body({
                    "prio": (np.abs(r.normal(size=n)) + 0.1)
                    .astype(np.float64),
                    "obs": o,
                    "action": r.integers(0, 2, n).astype(np.int32),
                    "reward": r.normal(size=n).astype(np.float32),
                    "discount": np.full(n, 0.99, np.float32),
                    "next_obs": np.roll(o, -1, axis=0),
                }, codec=CODEC_ZLIB)
                for srv in servers:
                    cli = ShardClient(0, "127.0.0.1", srv.port,
                                      token=9, client_id=100 + chunk,
                                      incarnation=1)
                    from ape_x_dqn_tpu.replay.service import OP_ADD
                    cli.request(OP_ADD, body, timeout=10.0)
                    cli.close()
            # The shard's pump thread must actually spill (the budget is
            # a fraction of the stored frames) before the proof runs, so
            # the crc scan REALLY faults spans back from the cold file.
            _wait(lambda: servers[1].spill_spans > 0, msg="spill sweep")
            assert tiered.frames_nbytes() < dense.frames_nbytes()
            digests = []
            for srv in servers:
                cli = ShardClient(0, "127.0.0.1", srv.port, token=9,
                                  client_id=55, incarnation=1)
                digests.append(cli.digest(with_crc=True, timeout=10.0))
                cli.close()
            dense_d, tiered_d = digests
            for key in ("count", "cursor", "size", "crc"):
                assert int(dense_d[key]) == int(tiered_d[key]), key
            assert abs(dense_d["total_mass"]
                       - tiered_d["total_mass"]) <= 1e-9
            assert servers[1].stats()["spill_bytes"] > 0
        finally:
            for srv in servers:
                srv.close()
