"""Incremental async replay checkpointing (utils/checkpoint_inc).

The properties the subsystem sells, adversarially:

  * chunk files are CRC-framed — truncation/bit-rot is detected, never
    half-applied;
  * the manifest is the atomic commit marker, written LAST — a SIGKILL
    barrage against a live writer always leaves a restorable chain, with
    uncommitted tails ignored;
  * replaying base + deltas is BIT-FOR-BIT equal to a full snapshot, for
    every replay implementation (PrioritizedReplay raw/compressed,
    DedupReplay, NativeDedupReplay, FusedDedupLearner dp=1 and dp>1);
  * dp>1 sharded-dedup kill/resume (the ROADMAP item): per-shard cursors,
    dropped_carry and frame_dead accounting survive, training continues;
  * the async writer applies backpressure (inflight skips) and surfaces
    its failures at the next save instead of dying silently.
"""

import json
import multiprocessing
import os
import signal
import time

import numpy as np
import pytest

from ape_x_dqn_tpu.replay import PrioritizedReplay
from ape_x_dqn_tpu.replay.dedup import DedupReplay
from ape_x_dqn_tpu.types import DedupChunk, NStepTransition
from ape_x_dqn_tpu.utils import checkpoint_inc as ci
from ape_x_dqn_tpu.utils.checkpoint_inc import (
    ChunkCorrupt,
    IncrementalCheckpointer,
    load_incremental_replay,
    read_chunk,
    read_manifest,
    write_chunk,
)

OBS = (6, 6, 1)


def np_chunk(M=8, seed=0):
    r = np.random.default_rng(seed)
    return NStepTransition(
        obs=r.integers(0, 255, (M, *OBS), dtype=np.uint8),
        action=r.integers(0, 3, (M,), dtype=np.int32),
        reward=r.normal(size=(M,)).astype(np.float32),
        discount=np.full((M,), 0.9, np.float32),
        next_obs=r.integers(0, 255, (M, *OBS), dtype=np.uint8),
    )


def dchunk(M=8, src=1, seq=0, seed=0, carry=0, obs=OBS):
    """One dedup chunk; ``carry`` > 0 makes the first rows reference the
    previous chunk's frames (negative refs — dropped on a seq gap)."""
    r = np.random.default_rng(seed)
    obs_ref = np.arange(M, dtype=np.int32)
    obs_ref[:carry] = -np.arange(1, carry + 1, dtype=np.int32)
    return DedupChunk(
        frames=r.integers(0, 255, (M + 1, *obs), dtype=np.uint8),
        obs_ref=obs_ref,
        next_ref=np.arange(1, M + 1, dtype=np.int32),
        action=r.integers(0, 3, M).astype(np.int32),
        reward=r.normal(size=M).astype(np.float32),
        discount=np.full(M, 0.9, np.float32),
        source=src,
        chunk_seq=seq,
        prev_frames=M + 1,
    )


def prio(M=8, seed=0):
    r = np.random.default_rng(seed + 1000)
    return (np.abs(r.normal(size=M)) + 0.1).astype(np.float32)


def assert_same_state(s1: dict, s2: dict):
    assert set(s1) == set(s2), (set(s1) ^ set(s2))
    for k in s1:
        np.testing.assert_array_equal(
            np.asarray(s1[k]), np.asarray(s2[k]), err_msg=k
        )


def churn(rep, seed=0, iters=4, B=4):
    """Sample + restamp — dirties sparse priorities between saves."""
    r = np.random.default_rng(seed)
    for _ in range(iters):
        batch = rep.sample(B, rng=r)
        rep.update_priorities(
            batch.indices, (np.abs(r.normal(size=B)) + 0.1).astype(np.float32)
        )


class TestChunkFormat:
    def test_roundtrip_preserves_dtypes_and_values(self, tmp_path):
        arrays = {
            "a": np.arange(7, dtype=np.int64),
            "b": np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32),
            "c": np.asarray(True),
            "d": np.zeros((0,), np.float64),
        }
        p = str(tmp_path / "c.ckpt")
        n = write_chunk(p, arrays)
        assert n == os.path.getsize(p)
        got = read_chunk(p)
        assert set(got) == set(arrays)
        for k in arrays:
            assert got[k].dtype == np.asarray(arrays[k]).dtype, k
            np.testing.assert_array_equal(got[k], arrays[k])

    def test_zlib_flag_roundtrip(self, tmp_path):
        arrays = {"x": np.zeros((1000,), np.int64)}  # compressible
        raw = str(tmp_path / "raw.ckpt")
        comp = str(tmp_path / "comp.ckpt")
        n_raw = write_chunk(raw, arrays)
        n_comp = write_chunk(comp, arrays, compress=True)
        assert n_comp < n_raw
        np.testing.assert_array_equal(read_chunk(comp)["x"], arrays["x"])

    def test_truncated_chunk_rejected(self, tmp_path):
        p = str(tmp_path / "c.ckpt")
        write_chunk(p, {"x": np.arange(100)})
        data = open(p, "rb").read()
        with open(p, "wb") as f:  # the SIGKILL-mid-write shape: a torn tail
            f.write(data[: len(data) - 7])
        with pytest.raises(ChunkCorrupt):
            read_chunk(p)

    def test_bitflip_fails_crc(self, tmp_path):
        p = str(tmp_path / "c.ckpt")
        write_chunk(p, {"x": np.arange(100)})
        data = bytearray(open(p, "rb").read())
        data[len(data) // 2] ^= 0x40
        open(p, "wb").write(bytes(data))
        with pytest.raises(ChunkCorrupt, match="crc"):
            read_chunk(p)

    def test_bad_magic_rejected(self, tmp_path):
        p = str(tmp_path / "c.ckpt")
        with open(p, "wb") as f:
            f.write(b"NOPE" + b"\0" * 64)
        with pytest.raises(ChunkCorrupt, match="magic"):
            read_chunk(p)


class TestManifestCommit:
    def _chain(self, tmp_path, saves=3):
        rep = PrioritizedReplay(256, OBS)
        ck = IncrementalCheckpointer(str(tmp_path), rep, sync=True)
        for k in range(saves):
            rep.add(prio(seed=k), np_chunk(seed=k))
            churn(rep, seed=k)
            ck.save(k + 1)
        return rep

    def test_uncommitted_tail_and_tmp_files_ignored(self, tmp_path):
        rep = self._chain(tmp_path)
        d = ci.inc_dir(str(tmp_path))
        manifest = read_manifest(d)
        # A killed writer's leavings: a torn chunk file beyond the manifest
        # and a half-written manifest tmp — neither is referenced.
        with open(os.path.join(d, "chunk_0_99.ckpt"), "wb") as f:
            f.write(b"APXC" + b"\x01\0\0\0garbage")
        with open(os.path.join(d, "MANIFEST.json.tmp"), "w") as f:
            f.write('{"truncat')
        rep2 = PrioritizedReplay(256, OBS)
        assert load_incremental_replay(str(tmp_path), rep2) == 3
        assert_same_state(rep.state_dict(), rep2.state_dict())

    def test_corrupt_referenced_chunk_raises(self, tmp_path):
        self._chain(tmp_path)
        d = ci.inc_dir(str(tmp_path))
        name = read_manifest(d)["chunks"][-1]
        data = bytearray(open(os.path.join(d, name), "rb").read())
        data[-1] ^= 0x01
        open(os.path.join(d, name), "wb").write(bytes(data))
        with pytest.raises(ChunkCorrupt):
            load_incremental_replay(str(tmp_path), PrioritizedReplay(256, OBS))

    def test_no_manifest_means_no_chain(self, tmp_path):
        assert load_incremental_replay(
            str(tmp_path), PrioritizedReplay(256, OBS)
        ) is None
        os.makedirs(ci.inc_dir(str(tmp_path)))
        # chunks without a manifest (killed before the first commit)
        write_chunk(os.path.join(ci.inc_dir(str(tmp_path)), "chunk_0_0.ckpt"),
                    {"x": np.arange(3)})
        assert load_incremental_replay(
            str(tmp_path), PrioritizedReplay(256, OBS)
        ) is None


def _kill_victim(root: str) -> None:
    """Barrage child: add/churn/save as fast as possible until SIGKILLed."""
    rep = PrioritizedReplay(512, OBS)
    ck = IncrementalCheckpointer(root, rep, sync=True, base_every=3)
    step = 0
    while True:
        rep.add(prio(seed=step), np_chunk(seed=step))
        if step % 2:
            churn(rep, seed=step)
        step += 1
        ck.save(step)


class TestSigkillBarrage:
    def test_kill_mid_write_always_restores_last_manifest(self, tmp_path):
        """tests/test_shm_ring.py's kill-barrage style against the writer:
        children SIGKILLed at random moments mid-chain; every survivor dir
        must restore from its newest committed manifest, with the restored
        counters matching the manifest's chain_mark exactly."""
        ctx = multiprocessing.get_context("fork")
        rng = np.random.default_rng(0)
        for round_i in range(3):
            root = str(tmp_path / f"r{round_i}")
            proc = ctx.Process(target=_kill_victim, args=(root,), daemon=True)
            proc.start()
            try:
                deadline = time.monotonic() + 60.0
                while read_manifest(ci.inc_dir(root)) is None:
                    assert proc.is_alive(), "victim died on its own"
                    assert time.monotonic() < deadline, "no commit within 60s"
                    time.sleep(0.01)
                time.sleep(float(rng.uniform(0.02, 0.25)))
            finally:
                os.kill(proc.pid, signal.SIGKILL)
                proc.join(10.0)
            manifest = read_manifest(ci.inc_dir(root))
            rep = PrioritizedReplay(512, OBS)
            step = load_incremental_replay(root, rep)
            assert step == manifest["step"]
            state = rep.state_dict()
            assert [int(state["count"])] == manifest["chain_mark"]
            assert int(state["count"]) >= 8  # at least the first save's rows


class TestDeltaChainEqualsFull:
    @pytest.mark.parametrize("compressed", [False, True])
    def test_prioritized_replay(self, tmp_path, compressed):
        rep = PrioritizedReplay(64, OBS, frame_compression=compressed)
        ck = IncrementalCheckpointer(str(tmp_path), rep, sync=True)
        for k in range(5):  # wraps the 64-slot ring (5 × 16 rows)
            rep.add(prio(16, seed=k), np_chunk(16, seed=k))
            churn(rep, seed=k)
            ck.save(k + 1)
        stats = ck.stats()
        assert stats["bases"] == 1 and stats["deltas"] == 4
        rep2 = PrioritizedReplay(64, OBS, frame_compression=compressed)
        assert load_incremental_replay(str(tmp_path), rep2) == 5
        assert_same_state(rep.state_dict(), rep2.state_dict())
        # The restored replay keeps the chain alive: another delta applies.
        rep.add(prio(16, seed=9), np_chunk(16, seed=9))
        rep2.apply_delta_state_dict(rep.delta_state_dict())
        assert_same_state(rep.state_dict(), rep2.state_dict())

    def test_delta_bytes_track_interval_not_capacity(self, tmp_path):
        rep = PrioritizedReplay(4096, OBS)
        ck = IncrementalCheckpointer(str(tmp_path), rep, sync=True)
        for k in range(16):  # 1024 occupied rows — the base's footprint
            rep.add(prio(64, seed=100 + k), np_chunk(64, seed=100 + k))
        ck.save(1)
        base_bytes = ck.stats()["last_chunk_bytes"]
        rep.add(prio(64, seed=1), np_chunk(64, seed=1))
        ck.save(2)
        delta_one = ck.stats()["last_chunk_bytes"]
        for k in range(2, 4):
            rep.add(prio(64, seed=k), np_chunk(64, seed=k))
        ck.save(3)
        delta_two = ck.stats()["last_chunk_bytes"]
        assert delta_one < base_bytes
        # 2x the written span ⇒ ~2x the delta bytes (framing epsilon).
        assert 1.7 < delta_two / delta_one < 2.3

    def test_dedup_replay_with_sweep_and_carry_accounting(self, tmp_path):
        rep = DedupReplay(64, OBS, frame_ratio=1.25)
        ck = IncrementalCheckpointer(str(tmp_path), rep, sync=True)
        seq = {1: 0, 2: 0}
        k = 0

        def feed(src, gap=False):
            nonlocal k
            if gap:
                seq[src] += 2  # skip one chunk_seq → carry rows drop
            rep.add(prio(seed=k),
                    dchunk(src=src, seq=seq[src], seed=k, carry=2))
            seq[src] += 1
            k += 1

        feed(1)
        feed(2)
        ck.save(1)
        # Enough frames to wrap the 80-slot frame ring → liveness sweep
        # kills old rows (frame_dead), plus one deliberate carry gap.
        for i in range(6):
            feed(1, gap=(i == 2))
            feed(2)
            churn(rep, seed=i, B=2)
            ck.save(2 + i)
        state = rep.state_dict()
        assert int(state["frame_dead"]) > 0
        assert int(state["dropped_carry"]) > 0
        rep2 = DedupReplay(64, OBS, frame_ratio=1.25)
        assert load_incremental_replay(str(tmp_path), rep2) == 7
        assert_same_state(state, rep2.state_dict())
        assert rep2._resolver.dropped_carry == rep._resolver.dropped_carry
        assert rep2._frame_dead == rep._frame_dead

    def test_native_dedup_bit_for_bit_and_cross_impl(self, tmp_path):
        from ape_x_dqn_tpu.replay.native_dedup import (
            NativeDedupReplay,
            native_dedup_available,
            native_dedup_error,
        )

        if not native_dedup_available():
            pytest.skip(f"native core unavailable: {native_dedup_error()}")
        rep = NativeDedupReplay(64, OBS, frame_ratio=1.25)
        ck = IncrementalCheckpointer(str(tmp_path), rep, sync=True)
        # Two interleaved sources (the shape that strands live transitions
        # past their frames — per-source spans interleave in the shared
        # ring, so one source's sweep catches the other's rows), same feed
        # as test_dedup_replay_with_sweep_and_carry_accounting.
        seq = {1: 0, 2: 0}
        k = 0

        def feed(src, gap=False):
            nonlocal k
            if gap:
                seq[src] += 2
            rep.add(prio(seed=k),
                    dchunk(src=src, seq=seq[src], seed=k, carry=2))
            seq[src] += 1
            k += 1

        feed(1)
        feed(2)
        ck.save(1)
        for i in range(6):
            feed(1, gap=(i == 2))
            feed(2)
            churn(rep, seed=i, B=2)
            ck.save(2 + i)
        state = rep.state_dict()
        assert int(state["frame_dead"]) > 0
        assert int(state["dropped_carry"]) > 0
        # Same chain, restored into BOTH implementations — the numpy twin
        # stays the native core's oracle through checkpointing.
        rep_native = NativeDedupReplay(64, OBS, frame_ratio=1.25)
        assert load_incremental_replay(str(tmp_path), rep_native) == 7
        assert_same_state(state, rep_native.state_dict())
        rep_py = DedupReplay(64, OBS, frame_ratio=1.25)
        assert load_incremental_replay(str(tmp_path), rep_py) == 7
        assert_same_state(state, rep_py.state_dict())

    def test_chain_discontinuity_raises(self, tmp_path):
        rep = PrioritizedReplay(64, OBS)
        rep.add(prio(seed=0), np_chunk(seed=0))
        rep.delta_state_dict()  # mark
        rep.add(prio(seed=1), np_chunk(seed=1))
        delta = rep.delta_state_dict()
        other = PrioritizedReplay(64, OBS)
        other.add(prio(16, seed=7), np_chunk(16, seed=7))  # count 16 != 8
        with pytest.raises(ValueError, match="discontinuity"):
            other.apply_delta_state_dict(delta)
        with pytest.raises(ValueError, match="delta"):
            other.apply_delta_state_dict(other.state_dict())


class _SlowLeaf:
    """np.asarray(…) on the writer thread blocks — deterministic way to
    hold the writer busy and exercise backpressure."""

    def __init__(self, hold: float):
        self._hold = hold

    def __array__(self, dtype=None, copy=None):
        time.sleep(self._hold)
        return np.zeros((4,), np.float32)


class _DegradedReplay:
    """state_dict/load_state_dict only — no delta protocol."""

    def __init__(self, hold: float = 0.0):
        self.hold = hold
        self.loaded = None

    def state_dict(self):
        leaf = _SlowLeaf(self.hold) if self.hold else np.arange(4.0)
        return {"x": leaf, "count": np.asarray([3], np.int64)}

    def load_state_dict(self, state):
        self.loaded = state


class TestAsyncWriter:
    def test_backpressure_counts_inflight_skips(self, tmp_path):
        rep = _DegradedReplay(hold=0.4)
        ck = IncrementalCheckpointer(str(tmp_path), rep)
        try:
            assert ck.save(1)           # writer now busy for ~0.4 s
            assert not ck.save(2)       # refused, not queued behind
            assert ck.stats()["inflight_skips"] == 1
            assert ck.flush(timeout=30.0)
            assert ck.save(3)           # drained — accepted again
            assert ck.flush(timeout=30.0)
            # Degraded replays (no delta protocol) write a full base every
            # save, still committed manifest-last.
            assert ck.stats()["bases"] == 2
            m = read_manifest(ci.inc_dir(str(tmp_path)))
            assert m["step"] == 3 and len(m["chunks"]) == 1
        finally:
            ck.close()

    def test_writer_failure_surfaces_at_next_save(self, tmp_path):
        class Exploding:
            def __array__(self, dtype=None, copy=None):
                raise RuntimeError("disk on fire")

        class BadReplay:
            def state_dict(self):
                return {"x": Exploding()}

        ck = IncrementalCheckpointer(str(tmp_path), BadReplay())
        try:
            ck.save(1)
            ck.flush(timeout=30.0)
            pytest.fail("flush must re-raise the writer failure")
        except RuntimeError as e:
            assert "checkpoint writer failed" in str(e)
        finally:
            ck.close(timeout=1.0) if ck.error is None else None
        with pytest.raises(RuntimeError, match="checkpoint writer failed"):
            ck.save(2)

    def test_degraded_roundtrip(self, tmp_path):
        src = _DegradedReplay()
        ck = IncrementalCheckpointer(str(tmp_path), src, sync=True)
        ck.save(5)
        dst = _DegradedReplay()
        assert load_incremental_replay(str(tmp_path), dst) == 5
        np.testing.assert_array_equal(dst.loaded["x"], np.arange(4.0))


class TestFusedDedup:
    def _make(self, mesh=None, n=1):
        import jax
        import jax.numpy as jnp

        from ape_x_dqn_tpu.learner.train_step import (
            init_train_state,
            make_optimizer,
        )
        from ape_x_dqn_tpu.models.dueling import DuelingMLP
        from ape_x_dqn_tpu.runtime.fused_dedup import FusedDedupLearner

        net = DuelingMLP(num_actions=3, hidden_sizes=(16,))
        opt = make_optimizer("adam", learning_rate=1e-3)
        state = init_train_state(
            net, opt, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.uint8)
        )
        return FusedDedupLearner(
            net, opt, state, (8,), capacity=64 * n, batch_size=4 * n,
            steps_per_call=2, ingest_block=8 * n, target_sync_freq=4,
            mesh=mesh,
        )

    def _feed(self, fused, n, seqs, gap_at=None):
        for src in range(n):
            seq = seqs.get(src, 0)
            if gap_at is not None and src == gap_at:
                seq += 2  # chunk_seq gap → carried rows drop
            fused.add_chunk(
                prio(seed=src * 31 + seq),
                dchunk(src=src + 1, seq=seq, seed=src * 31 + seq,
                       carry=2 if seq else 0, obs=(8,)),
            )
            seqs[src] = seq + 1

    def test_single_shard_delta_equals_full(self, tmp_path):
        fused = self._make()
        seqs = {}
        for _ in range(3):
            self._feed(fused, 1, seqs)
        fused.ingest_staged(drain=True)
        ck = IncrementalCheckpointer(str(tmp_path), fused, sync=True)
        ck.save(1)
        fused.train(0.5)
        self._feed(fused, 1, seqs)
        fused.ingest_staged(drain=True)
        fused.train(0.5)
        ck.save(2)
        assert ck.stats()["deltas"] == 1
        fused2 = self._make()
        assert load_incremental_replay(str(tmp_path), fused2) == 2
        assert_same_state(fused.state_dict(), fused2.state_dict())
        m = fused2.train(0.5)
        assert np.isfinite(np.asarray(m.loss)).all()

    def test_dp2_sharded_kill_resume_accounting(self, tmp_path):
        """The ROADMAP dp>1 dedup-resume item, deterministically: a dp=2
        sharded dedup learner checkpoints mid-stream (base + delta, with a
        carry gap on one source), a fresh learner restores the chain —
        per-shard cursors/count/fcount bit-for-bit, dropped_carry
        accounted per resolver — and training continues monotonically."""
        from ape_x_dqn_tpu.parallel import make_mesh

        mesh = make_mesh(num_devices=2)
        fused = self._make(mesh=mesh, n=2)
        seqs = {}
        for _ in range(3):
            self._feed(fused, 2, seqs)
        fused.ingest_staged(drain=True)
        ck = IncrementalCheckpointer(str(tmp_path), fused, sync=True)
        ck.save(1)
        fused.train(0.5)
        # Mid-stream progress, with a carry gap on shard-1's source.
        self._feed(fused, 2, seqs, gap_at=1)
        fused.ingest_staged(drain=True)
        fused.train(0.5)
        ck.save(2)
        assert ck.stats()["deltas"] == 1
        dropped = [r.dropped_carry for r in fused._stager.resolvers]
        assert sum(dropped) > 0

        fused2 = self._make(mesh=mesh, n=2)
        assert load_incremental_replay(str(tmp_path), fused2) == 2
        s1, s2 = fused.state_dict(), fused2.state_dict()
        assert_same_state(s1, s2)
        # Per-shard cursors restored: [n]-shaped counters, both advanced.
        for key in ("cursor", "count", "fcount"):
            assert np.asarray(s2[key]).shape == (2,), key
        assert (np.asarray(s2["count"]) > 0).all()
        assert [r.dropped_carry for r in fused2._stager.resolvers] == dropped
        # Training continues monotonically off the restored ring.
        step0 = fused2.step
        m = fused2.train(0.5)
        assert np.isfinite(np.asarray(m.loss)).all()
        assert fused2.step == step0 + fused2.steps_per_call

    def test_delta_into_wrong_shard_count_rejected(self, tmp_path):
        fused = self._make()
        seqs = {}
        self._feed(fused, 1, seqs)
        fused.ingest_staged(drain=True)
        fused.delta_state_dict()  # mark
        self._feed(fused, 1, seqs)
        fused.ingest_staged(drain=True)
        delta = fused.delta_state_dict()
        from ape_x_dqn_tpu.parallel import make_mesh

        other = self._make(mesh=make_mesh(num_devices=2), n=2)
        with pytest.raises(ValueError, match="shard"):
            other.apply_delta_state_dict(delta)


class TestPipelineIntegration:
    def test_sigkill_resume_e2e_sharded_dedup(self, tmp_path):
        """Kill-and-resume a LIVE sharded-dedup run (device_replay + dedup
        + data_parallel=2) off live actors: SIGKILL mid-run, resume from
        the committed manifest, train past the restored step (the
        acceptance shape; tools/ckpt_smoke.py --dedup-dp is the same
        harness as a verify gate)."""
        from tools.ckpt_smoke import run_smoke

        out = run_smoke(str(tmp_path / "ckpt"), mode="dedup_dp",
                        kill_after_chunks=2, timeout_s=240.0)
        assert out["ok"]
        assert out["resumed_step"] == out["committed_step"] > 0
        assert out["continued_to_step"] > out["resumed_step"]
        assert out["replay_size_after_resume"] > 0

    def test_restore_missing_replay_emits_metrics_event(self, tmp_path,
                                                        capsys):
        """The degraded-restart WARNING is a structured JSONL event on the
        metrics stream (utils/metrics.emit_event), not a bare print."""
        import jax
        import jax.numpy as jnp

        from ape_x_dqn_tpu.learner.train_step import (
            init_train_state,
            make_optimizer,
        )
        from ape_x_dqn_tpu.models.dueling import DuelingMLP
        from ape_x_dqn_tpu.utils.checkpoint import (
            restore_checkpoint,
            save_checkpoint,
        )

        net = DuelingMLP(num_actions=3, hidden_sizes=(16,))
        state = init_train_state(
            net, make_optimizer("adam"), jax.random.PRNGKey(0),
            jnp.zeros((1, 8), jnp.uint8),
        )
        save_checkpoint(str(tmp_path), state)  # no replay leg
        capsys.readouterr()
        replay = PrioritizedReplay(64, OBS)
        restore_checkpoint(str(tmp_path), state, replay=replay)
        err = capsys.readouterr().err
        events = [json.loads(line) for line in err.splitlines()
                  if line.startswith("{")]
        assert any(
            e.get("event") == "checkpoint_restore_missing_replay"
            for e in events
        ), err

    def test_metric_logger_event_is_out_of_band(self):
        """MetricLogger.event: an immediate JSONL record that leaves the
        scalar accumulators untouched (events are occurrences, not window
        statistics)."""
        import io

        from ape_x_dqn_tpu.utils.metrics import MetricLogger

        buf = io.StringIO()
        log = MetricLogger(stream=buf)
        log.log("a", 1.0)
        rec = log.event("salvage", worker=3)
        # Payload plus the universal (seq, pid) merge stamps — the
        # multi-process ordering contract (docs/METRICS.md).
        assert rec["event"] == "salvage" and rec["worker"] == 3
        assert set(rec) == {"event", "worker", "seq", "pid"}
        lines = [json.loads(line) for line in buf.getvalue().splitlines()]
        assert rec in lines                      # written immediately
        assert log.emit()["a"] == 1.0            # accumulator survived

    def test_restore_prefers_npz_then_falls_back_to_chain(self, tmp_path):
        from ape_x_dqn_tpu.utils.checkpoint import load_replay_leg

        rep = PrioritizedReplay(64, OBS)
        rep.add(prio(seed=0), np_chunk(seed=0))
        ck = IncrementalCheckpointer(str(tmp_path), rep, sync=True)
        ck.save(1)
        # No step-dir npz → the chain restores.
        rep2 = PrioritizedReplay(64, OBS)
        assert load_replay_leg(str(tmp_path), rep2) == "incremental"
        assert_same_state(rep.state_dict(), rep2.state_dict())
        assert load_replay_leg(str(tmp_path / "nope"),
                               PrioritizedReplay(64, OBS)) is None


# ---------------------------------------------------------------------------
# Restore under corruption (ISSUE 6 satellite): flip one byte / truncate
# each chunk kind — base, delta, manifest-missing — across all five replay
# flavors, and assert EITHER exact recovery (the live generation's longest
# good prefix, or the previous committed generation) OR a typed failure.
# Never a wrong-data load, never a raw struct/zlib traceback.
# ---------------------------------------------------------------------------


def _make_fused(n=1):
    import jax
    import jax.numpy as jnp

    from ape_x_dqn_tpu.learner.train_step import (
        init_train_state,
        make_optimizer,
    )
    from ape_x_dqn_tpu.models.dueling import DuelingMLP
    from ape_x_dqn_tpu.runtime.fused_dedup import FusedDedupLearner

    mesh = None
    if n > 1:
        from ape_x_dqn_tpu.parallel import make_mesh

        mesh = make_mesh(num_devices=n)
    net = DuelingMLP(num_actions=3, hidden_sizes=(16,))
    opt = make_optimizer("adam", learning_rate=1e-3)
    state = init_train_state(
        net, opt, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.uint8)
    )
    return FusedDedupLearner(
        net, opt, state, (8,), capacity=64 * n, batch_size=4 * n,
        steps_per_call=2, ingest_block=8 * n, target_sync_freq=4,
        mesh=mesh,
    )


def _fused_feed(n):
    def feed(fused, k):
        for src in range(n):
            fused.add_chunk(
                prio(seed=src * 31 + k),
                dchunk(src=src + 1, seq=k, seed=src * 31 + k,
                       carry=2 if k else 0, obs=(8,)),
            )
        fused.ingest_staged(drain=True)
    return feed


def _dedup_feed(make_chunk=dchunk):
    def feed(rep, k):
        rep.add(prio(seed=k), make_chunk(src=1, seq=k, seed=k,
                                         carry=2 if k else 0))
        churn(rep, seed=k, B=2)
    return feed


def _np_feed(rep, k):
    rep.add(prio(16, seed=k), np_chunk(16, seed=k))
    churn(rep, seed=k)


def _flavor(name):
    """(make_fn, feed_fn) per replay flavor; skips where unavailable."""
    if name == "prioritized":
        return (lambda: PrioritizedReplay(64, OBS)), _np_feed
    if name == "dedup":
        return (lambda: DedupReplay(64, OBS, frame_ratio=1.25)), _dedup_feed()
    if name == "native_dedup":
        from ape_x_dqn_tpu.replay.native_dedup import (
            NativeDedupReplay,
            native_dedup_available,
            native_dedup_error,
        )

        if not native_dedup_available():
            pytest.skip(f"native core unavailable: {native_dedup_error()}")
        return (lambda: NativeDedupReplay(64, OBS, frame_ratio=1.25)), \
            _dedup_feed()
    if name == "fused_dp1":
        return (lambda: _make_fused(1)), _fused_feed(1)
    if name == "fused_dp2":
        return (lambda: _make_fused(2)), _fused_feed(2)
    if name == "tiered_dedup":
        # Cold-tier dedup (replay/tiered.py): every make() shares ONE
        # spill dir, so restores exercise the adopt-in-place path and a
        # corrupt chunk's fallback walk re-verifies cold refs.  A tiny
        # hot budget keeps most spans cold through the whole matrix.
        import tempfile

        spill = tempfile.mkdtemp(prefix="apex-tier-flavor-")

        def make_tiered():
            rep = DedupReplay(64, OBS, frame_ratio=1.25,
                              hot_frame_budget_bytes=512,
                              spill_dir=spill, spill_span_frames=4)
            return rep

        base_feed = _dedup_feed()

        def feed_and_spill(rep, k):
            base_feed(rep, k)
            rep.spill_cold()

        return make_tiered, feed_and_spill
    raise ValueError(name)


FLAVORS = ["prioritized", "dedup", "native_dedup", "fused_dp1", "fused_dp2",
           "tiered_dedup"]


class TestRestoreUnderCorruption:
    def _chain(self, root, make, feed, saves=6, base_every=2):
        """Build a two-generation chain; returns per-save state snapshots
        (materialized copies — the live buffers keep mutating) and the
        final manifest."""
        rep = make()
        ck = IncrementalCheckpointer(str(root), rep, base_every=base_every,
                                     sync=True)
        states = {}
        for k in range(saves):
            feed(rep, k)
            ck.save(k + 1)
            states[k + 1] = {
                key: np.array(np.asarray(v))
                for key, v in rep.state_dict().items()
            }
        manifest = ci.read_manifest(ci.inc_dir(str(root)))
        assert manifest["generation"] >= 1, "chain must span 2 generations"
        assert manifest["chunk_steps"], "manifest must carry per-chunk steps"
        return states, manifest

    def _corrupt(self, root, chunk_name, mode):
        path = os.path.join(ci.inc_dir(str(root)), chunk_name)
        if mode == "bitflip":
            with open(path, "r+b") as f:
                f.seek(40)
                b = f.read(1)
                f.seek(40)
                f.write(bytes([b[0] ^ 0x20]))
        else:  # truncate to header-only
            with open(path, "r+b") as f:
                f.truncate(20)
        return path

    @pytest.mark.parametrize("flavor", FLAVORS)
    @pytest.mark.parametrize("mode", ["bitflip", "truncate"])
    def test_corrupt_delta_exact_prefix_recovery_or_typed(
            self, tmp_path, flavor, mode):
        make, feed = _flavor(flavor)
        root = tmp_path / f"{flavor}-{mode}-delta"
        states, manifest = self._chain(root, make, feed)
        self._corrupt(root, manifest["chunks"][-1], mode)
        # Without fallback: typed failure, never a raw decode error.
        with pytest.raises(ChunkCorrupt) as ei:
            load_incremental_replay(str(root), make())
        assert ei.value.generation == manifest["generation"]
        # With fallback: EXACT recovery to the previous delta's step.
        rep2 = make()
        step = load_incremental_replay(str(root), rep2, fallback=True)
        want = manifest["chunk_steps"][-2]
        assert step == want
        assert_same_state(states[want], rep2.state_dict())
        events = ci.consume_fallback_events()
        assert events and events[-1]["fallback"] == "partial_chain"

    @pytest.mark.parametrize("flavor", FLAVORS)
    @pytest.mark.parametrize("mode", ["bitflip", "truncate"])
    def test_corrupt_base_recovers_previous_generation_exactly(
            self, tmp_path, flavor, mode):
        make, feed = _flavor(flavor)
        root = tmp_path / f"{flavor}-{mode}-base"
        states, manifest = self._chain(root, make, feed)
        self._corrupt(root, manifest["chunks"][0], mode)
        with pytest.raises(ChunkCorrupt):
            load_incremental_replay(str(root), make())
        rep2 = make()
        step = load_incremental_replay(str(root), rep2, fallback=True)
        prev = ci.read_archived_manifest(
            ci.inc_dir(str(root)), manifest["generation"] - 1
        )
        assert step == prev["step"]
        assert_same_state(states[step], rep2.state_dict())
        events = ci.consume_fallback_events()
        assert events and events[-1]["fallback"] == "previous_generation"

    @pytest.mark.parametrize("flavor", FLAVORS)
    def test_manifest_missing_is_no_chain_not_wrong_data(
            self, tmp_path, flavor):
        make, feed = _flavor(flavor)
        root = tmp_path / f"{flavor}-nomanifest"
        self._chain(root, make, feed)
        os.unlink(os.path.join(ci.inc_dir(str(root)), "MANIFEST.json"))
        assert load_incremental_replay(str(root), make()) is None
        assert load_incremental_replay(str(root), make(),
                                       fallback=True) is None

    def test_every_rung_corrupt_is_typed_failure(self, tmp_path):
        make, feed = _flavor("prioritized")
        root = tmp_path / "all-rungs"
        _, manifest = self._chain(root, make, feed)
        # Kill the live generation's base AND the archived generation's.
        prev = ci.read_archived_manifest(
            ci.inc_dir(str(root)), manifest["generation"] - 1
        )
        self._corrupt(root, manifest["chunks"][0], "bitflip")
        self._corrupt(root, prev["chunks"][0], "truncate")
        with pytest.raises(ChunkCorrupt):
            load_incremental_replay(str(root), make(), fallback=True)
        ci.consume_fallback_events()  # nothing restored; drain any noise

    def test_corrupt_cold_span_record_is_typed_or_fallback(self, tmp_path):
        """Cold-tier rung of the matrix (ISSUE 7 satellite): a tiered
        base references spill-file records by offset; scribbling those
        records must surface as the SAME typed ChunkCorrupt contract as
        a torn chunk — restore without fallback raises, with fallback it
        either lands on a rung whose refs still verify (exact state) or
        raises typed.  Silently-wrong frames are the one forbidden
        outcome."""
        make, feed = _flavor("tiered_dedup")
        root = tmp_path / "cold-span"
        states, manifest = self._chain(root, make, feed)
        assert manifest.get("cold_ref_bytes", 0) > 0, (
            "matrix precondition: the tiered base must reference cold "
            "spans"
        )
        spill_file = manifest["spill_file"]
        with open(spill_file, "r+b") as f:
            size = os.fstat(f.fileno()).st_size
            for off in range(0, size, 128):  # break every record
                f.seek(off)
                f.write(b"\xde\xad")
        with pytest.raises(ChunkCorrupt):
            load_incremental_replay(str(root), make())
        rep2 = make()
        try:
            step = load_incremental_replay(str(root), rep2, fallback=True)
        except ChunkCorrupt:
            ci.consume_fallback_events()
            return  # typed all the way down — acceptable per contract
        assert step in states
        assert_same_state(states[step], rep2.state_dict())

    def test_pruning_retains_one_prior_generation(self, tmp_path):
        make, feed = _flavor("prioritized")
        root = tmp_path / "retention"
        rep = make()
        ck = IncrementalCheckpointer(str(root), rep, base_every=1, sync=True)
        for k in range(8):  # many generations
            feed(rep, k)
            ck.save(k + 1)
        manifest = ci.read_manifest(ci.inc_dir(str(root)))
        live = manifest["generation"]
        gens = sorted({
            int(n.split("_")[1])
            for n in os.listdir(ci.inc_dir(str(root)))
            if n.startswith("chunk_")
        })
        # Exactly the live generation plus its fallback rung survive.
        assert gens == [live - 1, live]
        assert ci.read_archived_manifest(ci.inc_dir(str(root)), live - 1)
