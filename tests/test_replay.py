"""Replay subsystem tests: sum-tree invariants + prioritized buffer semantics.

SURVEY §4 test level 1 (sum-tree invariants) and the intended central-replay
semantics of reference replay.py (proportional p^α sampling, priority upsert,
FIFO eviction with priorities evicted too, IS weights)."""

import numpy as np
import pytest

from ape_x_dqn_tpu.replay import PrioritizedReplay, SumTree
from ape_x_dqn_tpu.types import NStepTransition


def make_batch(n, obs_shape=(4, 4, 1), seed=0):
    r = np.random.default_rng(seed)
    return NStepTransition(
        obs=r.integers(0, 255, (n, *obs_shape), dtype=np.uint8),
        action=r.integers(0, 4, (n,), dtype=np.int32),
        reward=r.normal(size=(n,)).astype(np.float32),
        discount=np.full((n,), 0.9, np.float32),
        next_obs=r.integers(0, 255, (n, *obs_shape), dtype=np.uint8),
    )


class TestSumTree:
    def test_total_matches_sum(self, rng):
        t = SumTree(100)
        idx = rng.permutation(100)[:50]
        pri = rng.random(50)
        t.set(idx, pri)
        assert np.isclose(t.total, pri.sum())
        assert np.allclose(t.get(idx), pri)

    def test_overwrite_updates_total(self):
        t = SumTree(8)
        t.set(np.arange(8), np.ones(8))
        t.set(np.array([3]), np.array([5.0]))
        assert np.isclose(t.total, 7 + 5)

    def test_duplicate_indices_last_write_wins(self):
        t = SumTree(4)
        t.set(np.array([2, 2, 2]), np.array([1.0, 7.0, 3.0]))
        assert t.get(np.array([2]))[0] == 3.0
        assert np.isclose(t.total, 3.0)

    def test_non_pow2_capacity(self):
        t = SumTree(5)
        t.set(np.arange(5), np.arange(1.0, 6.0))
        assert np.isclose(t.total, 15.0)

    def test_sample_inverse_cdf_exact(self):
        t = SumTree(4)
        t.set(np.arange(4), np.array([1.0, 2.0, 3.0, 4.0]))
        # Prefix intervals: [0,1) [1,3) [3,6) [6,10)
        targets = np.array([0.5, 1.0, 2.99, 3.0, 5.999, 6.0, 9.999])
        assert list(t.sample(targets)) == [0, 1, 1, 2, 2, 3, 3]

    def test_sampling_distribution_proportional(self, rng):
        t = SumTree(16)
        pri = np.arange(1.0, 17.0)
        t.set(np.arange(16), pri)
        idx = t.sample_stratified(200_000, rng)
        freq = np.bincount(idx, minlength=16) / 200_000
        assert np.allclose(freq, pri / pri.sum(), atol=5e-3)

    def test_zero_mass_leaf_never_sampled(self, rng):
        t = SumTree(8)
        t.set(np.array([1, 5]), np.array([3.0, 2.0]))
        idx = t.sample_stratified(10_000, rng)
        assert set(np.unique(idx)) <= {1, 5}

    def test_rejects_bad_input(self):
        t = SumTree(4)
        with pytest.raises(IndexError):
            t.set(np.array([4]), np.array([1.0]))
        with pytest.raises(ValueError):
            t.set(np.array([0]), np.array([-1.0]))
        with pytest.raises(ValueError):
            t.set(np.array([0]), np.array([np.nan]))
        with pytest.raises(ValueError):
            t.sample_stratified(4, np.random.default_rng(0))


class TestPrioritizedReplay:
    def test_add_and_size(self):
        rep = PrioritizedReplay(64, (4, 4, 1))
        rep.add(np.ones(10), make_batch(10))
        assert rep.size() == 10

    def test_roundtrip_contents(self):
        rep = PrioritizedReplay(64, (4, 4, 1))
        batch = make_batch(8, seed=3)
        rep.add(np.full(8, 1.0), batch)
        out = rep.sample(32, rng=np.random.default_rng(0))
        # Every sampled transition must be one we inserted, intact.
        for j in range(32):
            i = int(out.indices[j])
            assert np.array_equal(out.transition.obs[j], batch.obs[i])
            assert out.transition.action[j] == batch.action[i]
            assert out.transition.reward[j] == pytest.approx(float(batch.reward[i]))
            assert np.array_equal(out.transition.next_obs[j], batch.next_obs[i])

    def test_fifo_eviction_evicts_priorities(self):
        """Reference defect (SURVEY §2.8): evicted keys' priorities leak
        forever.  Here an overwritten slot carries ONLY its new priority."""
        rep = PrioritizedReplay(4, (2, 2, 1))
        rep.add(np.full(4, 100.0), make_batch(4, (2, 2, 1), seed=1))
        # Wrap: 2 new transitions with tiny priority overwrite slots 0-1.
        rep.add(np.full(2, 1e-6), make_batch(2, (2, 2, 1), seed=2))
        assert rep.size() == 4
        # Slots 0,1 now hold the tiny priorities, not the old 100s.
        tree_mass = rep._tree.get(np.array([0, 1]))
        assert np.all(tree_mass < 1.0)

    def test_proportional_sampling_respects_alpha(self, rng):
        rep = PrioritizedReplay(2, (2, 2, 1), priority_exponent=0.5)
        rep.add(np.array([1.0, 16.0]), make_batch(2, (2, 2, 1)))
        out_counts = np.zeros(2)
        for _ in range(200):
            out = rep.sample(64, rng=rng)
            out_counts += np.bincount(out.indices, minlength=2)
        # p^0.5 → masses 1:4 → slot 1 sampled ~80%.
        frac = out_counts[1] / out_counts.sum()
        assert abs(frac - 0.8) < 0.02

    def test_is_weights(self, rng):
        rep = PrioritizedReplay(4, (2, 2, 1), priority_exponent=1.0)
        rep.add(np.array([1.0, 1.0, 2.0, 4.0]), make_batch(4, (2, 2, 1)))
        out = rep.sample(256, beta=1.0, rng=rng)
        # w_i ∝ 1/P(i); rarest transition gets weight 1 (max-normalized).
        rare = out.is_weights[out.indices <= 1]
        common = out.is_weights[out.indices == 3]
        assert rare.size and common.size
        assert np.allclose(rare, 1.0)
        assert np.allclose(common, 0.25)

    def test_update_priorities_changes_distribution(self, rng):
        rep = PrioritizedReplay(2, (2, 2, 1), priority_exponent=1.0)
        rep.add(np.array([1.0, 1.0]), make_batch(2, (2, 2, 1)))
        rep.update_priorities(np.array([0]), np.array([1e4]))
        out = rep.sample(1000, rng=rng)
        assert np.mean(out.indices == 0) > 0.99

    def test_empty_sample_raises(self):
        rep = PrioritizedReplay(4, (2, 2, 1))
        with pytest.raises(ValueError):
            rep.sample(4)

    def test_snapshot_roundtrip(self, rng):
        rep = PrioritizedReplay(16, (2, 2, 1))
        rep.add(rng.random(10) + 0.1, make_batch(10, (2, 2, 1), seed=5))
        state = rep.state_dict()
        rep2 = PrioritizedReplay(16, (2, 2, 1))
        rep2.load_state_dict(state)
        assert rep2.size() == 10
        assert np.isclose(rep2._tree.total, rep._tree.total)
        out = rep2.sample(8, rng=np.random.default_rng(1))
        assert out.transition.obs.shape == (8, 2, 2, 1)

    def test_threaded_add_sample_update(self):
        """Many writers + one sampler/updater, no crashes, sane state."""
        import threading

        rep = PrioritizedReplay(512, (2, 2, 1))
        rep.add(np.ones(32), make_batch(32, (2, 2, 1)))
        stop = threading.Event()
        errors = []

        def writer(seed):
            try:
                r = np.random.default_rng(seed)
                while not stop.is_set():
                    rep.add(r.random(16) + 0.01, make_batch(16, (2, 2, 1), seed=seed))
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=writer, args=(s,)) for s in range(4)]
        for t in threads:
            t.start()
        r = np.random.default_rng(9)
        for _ in range(50):
            out = rep.sample(64, rng=r)
            rep.update_priorities(out.indices, np.abs(r.normal(size=64)) + 0.01)
        stop.set()
        for t in threads:
            t.join()
        assert not errors
        assert rep.size() == 512


class TestFrameCompression:
    """frame_compression=True (the reference's README TODO,
    README.md:24): identical sampling semantics, deflated frame storage."""

    def _structured_frames(self, n, shape=(84, 84, 1)):
        # Atari-like frames: large flat regions -> compressible.
        r = np.random.default_rng(0)
        base = np.zeros((n, *shape), np.uint8)
        base[:, 20:30, :, :] = r.integers(0, 255, (n, 10, shape[1], 1))
        return base

    def _chunk(self, n):
        frames = self._structured_frames(n)
        return NStepTransition(
            obs=frames,
            action=np.arange(n, dtype=np.int32) % 3,
            reward=np.ones(n, np.float32),
            discount=np.full(n, 0.9, np.float32),
            next_obs=frames[::-1].copy(),
        )

    def test_roundtrip_matches_raw(self):
        raw = PrioritizedReplay(64, (84, 84, 1))
        comp = PrioritizedReplay(64, (84, 84, 1), frame_compression=True)
        chunk = self._chunk(32)
        prio = np.abs(np.random.default_rng(1).normal(size=32)) + 0.1
        raw.add(prio, chunk)
        comp.add(prio, chunk)
        rng1 = np.random.default_rng(5)
        rng2 = np.random.default_rng(5)
        b_raw = raw.sample(16, rng=rng1)
        b_comp = comp.sample(16, rng=rng2)
        np.testing.assert_array_equal(b_raw.indices, b_comp.indices)
        np.testing.assert_array_equal(
            b_raw.transition.obs, b_comp.transition.obs
        )
        np.testing.assert_array_equal(
            b_raw.transition.next_obs, b_comp.transition.next_obs
        )

    def test_memory_actually_shrinks(self):
        comp = PrioritizedReplay(64, (84, 84, 1), frame_compression=True)
        raw = PrioritizedReplay(64, (84, 84, 1))
        chunk = self._chunk(64)
        comp.add(np.ones(64), chunk)
        raw.add(np.ones(64), chunk)
        assert comp.frames_nbytes() < raw.frames_nbytes() / 3

    def test_snapshot_roundtrip_compressed(self):
        comp = PrioritizedReplay(64, (84, 84, 1), frame_compression=True)
        chunk = self._chunk(48)
        comp.add(np.ones(48), chunk)
        state = comp.state_dict()
        comp2 = PrioritizedReplay(64, (84, 84, 1), frame_compression=True)
        comp2.load_state_dict(state)
        assert comp2.size() == 48
        b = comp2.sample(8, rng=np.random.default_rng(0))
        assert b.transition.obs.shape == (8, 84, 84, 1)

    def test_compressed_snapshot_stays_compressed(self):
        comp = PrioritizedReplay(64, (84, 84, 1), frame_compression=True)
        chunk = self._chunk(48)
        comp.add(np.ones(48), chunk)
        state = comp.state_dict()
        # No dense frame arrays in the snapshot — blobs + lengths instead.
        assert "obs" not in state and "obs_blob" in state
        assert state["obs_blob"].nbytes < 48 * 84 * 84 // 3
        # Cross-restore into a RAW store still reconstructs the frames.
        raw = PrioritizedReplay(64, (84, 84, 1))
        raw.load_state_dict(state)
        assert raw.size() == 48
        idx = np.arange(8)
        np.testing.assert_array_equal(
            raw._obs.get(idx), np.asarray(chunk.obs)[:8]
        )
