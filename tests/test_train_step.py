"""Fused train-step tests: descent, target sync cadence, priorities."""

import jax
import jax.numpy as jnp
import numpy as np

from ape_x_dqn_tpu.learner.train_step import (
    StepMetrics,
    build_train_step,
    init_train_state,
    make_optimizer,
)
from ape_x_dqn_tpu.models.dueling import DuelingMLP
from ape_x_dqn_tpu.types import NStepTransition, PrioritizedBatch


def _make_batch(rng_key, B=16, obs_dim=6, A=3):
    ks = jax.random.split(rng_key, 4)
    t = NStepTransition(
        obs=jax.random.normal(ks[0], (B, obs_dim)),
        action=jax.random.randint(ks[1], (B,), 0, A),
        reward=jax.random.normal(ks[2], (B,)),
        discount=jnp.full((B,), 0.97),
        next_obs=jax.random.normal(ks[3], (B, obs_dim)),
    )
    return PrioritizedBatch(
        transition=t,
        indices=jnp.arange(B, dtype=jnp.int32),
        is_weights=jnp.ones((B,)),
    )


def _setup(target_sync_freq=4, loss_kind="huber", jit=True):
    net = DuelingMLP(num_actions=3, hidden_sizes=(32,))
    opt = make_optimizer("adam", learning_rate=1e-3)
    state = init_train_state(net, opt, jax.random.PRNGKey(0), jnp.zeros((1, 6)))
    step = build_train_step(
        net, opt, loss_kind=loss_kind, target_sync_freq=target_sync_freq, jit=jit
    )
    return net, state, step


def test_loss_decreases_on_repeated_batch():
    _, state, step = _setup(target_sync_freq=10_000)
    batch = _make_batch(jax.random.PRNGKey(1))
    first = None
    for _ in range(60):
        state, m = step(state, batch)
        if first is None:
            first = float(m.loss)
    assert float(m.loss) < first * 0.5
    assert np.isfinite(float(m.loss))


def test_target_sync_exactly_on_schedule():
    # Intended gate: copy every `freq` steps (reference inverts it, SURVEY §2.8).
    net, state, step = _setup(target_sync_freq=3)
    batch = _make_batch(jax.random.PRNGKey(2))

    def tdiff(s):
        return sum(
            float(jnp.sum(jnp.abs(a - b)))
            for a, b in zip(
                jax.tree_util.tree_leaves(s.params),
                jax.tree_util.tree_leaves(s.target_params),
            )
        )

    diffs = []
    for _ in range(6):
        state, _ = step(state, batch)
        diffs.append(tdiff(state))
    # steps 1,2: drifted; step 3: synced (diff 0); 4,5 drift; 6 synced.
    assert diffs[0] > 0 and diffs[1] > 0
    assert diffs[2] == 0.0
    assert diffs[3] > 0 and diffs[4] > 0
    assert diffs[5] == 0.0


def test_priorities_shape_and_positivity():
    _, state, step = _setup()
    batch = _make_batch(jax.random.PRNGKey(3), B=8)
    state, m = step(state, batch)
    p = np.asarray(m.priorities)
    assert p.shape == (8,)
    assert (p > 0).all()
    # not collapsed to a single value (reference defect)
    assert len(np.unique(p)) > 1


def test_step_counter_increments():
    _, state, step = _setup()
    batch = _make_batch(jax.random.PRNGKey(4))
    assert int(state.step) == 0
    state, _ = step(state, batch)
    state, _ = step(state, batch)
    assert int(state.step) == 2


def test_squared_parity_loss_mode():
    _, state, step = _setup(loss_kind="squared")
    batch = _make_batch(jax.random.PRNGKey(5))
    state, m = step(state, batch)
    assert np.isfinite(float(m.loss))


def test_bf16_params_with_f32_master_track_f32_training():
    """param_dtype=bfloat16 + with_float32_master must track a float32 run:
    the tiny RMSProp-scale updates (~lr) are below bf16 resolution, so
    without the master copy they'd round to zero — with it, loss falls the
    same way as the float32 run."""
    from ape_x_dqn_tpu.learner.train_step import with_float32_master

    def run(param_dtype, wrap):
        net = DuelingMLP(num_actions=3, hidden_sizes=(32,),
                         param_dtype=param_dtype)
        opt = make_optimizer("rmsprop", learning_rate=1e-3, max_grad_norm=None)
        if wrap:
            opt = with_float32_master(opt)
        state = init_train_state(net, opt, jax.random.PRNGKey(0),
                                 jnp.zeros((1, 6)))
        step = build_train_step(net, opt, target_sync_freq=100, jit=False)
        batch = _make_batch(jax.random.PRNGKey(1))
        losses = []
        for _ in range(60):
            state, metrics = step(state, batch)
            losses.append(float(metrics.loss))
        return state, losses

    s16, l16 = run(jnp.bfloat16, wrap=True)
    s32, l32 = run(jnp.float32, wrap=False)
    # Params stayed bf16; master copy lives in opt state as f32.
    leaf16 = jax.tree_util.tree_leaves(s16.params)[0]
    assert leaf16.dtype == jnp.bfloat16
    master_leaf = jax.tree_util.tree_leaves(s16.opt_state[0])[0]
    assert master_leaf.dtype == jnp.float32
    # Same descent trajectory within bf16 forward noise.
    assert l16[-1] < l16[0] * 0.7
    assert abs(l16[-1] - l32[-1]) < 0.25 * abs(l32[0]) + 0.05

    # Low-precision params track cast(master) exactly (the Sterbenz add).
    master = s16.opt_state[0]
    for m, p in zip(jax.tree_util.tree_leaves(master),
                    jax.tree_util.tree_leaves(s16.params)):
        np.testing.assert_array_equal(
            np.asarray(m.astype(jnp.bfloat16)), np.asarray(p)
        )
