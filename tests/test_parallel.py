"""Distributed-semantics tests on 8 virtual CPU devices (SURVEY §4 level 4:
pjit sharding + collectives without hardware — conftest.py forces
xla_force_host_platform_device_count=8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ape_x_dqn_tpu.learner.train_step import (
    build_train_step,
    init_train_state,
    make_optimizer,
)
from ape_x_dqn_tpu.models.dueling import DuelingMLP, build_network
from ape_x_dqn_tpu.parallel import (
    build_sharded_train_step,
    infer_param_sharding,
    make_mesh,
    place_batch,
    shard_train_state,
)
from ape_x_dqn_tpu.types import NStepTransition, PrioritizedBatch


def make_batch(B, obs_shape=(12,), num_actions=3, seed=0):
    r = np.random.default_rng(seed)
    return PrioritizedBatch(
        transition=NStepTransition(
            obs=r.integers(0, 255, (B, *obs_shape), dtype=np.uint8),
            action=r.integers(0, num_actions, (B,), dtype=np.int32),
            reward=r.normal(size=(B,)).astype(np.float32),
            discount=np.full((B,), 0.95, np.float32),
            next_obs=r.integers(0, 255, (B, *obs_shape), dtype=np.uint8),
        ),
        indices=np.arange(B, dtype=np.int32),
        is_weights=np.ones((B,), np.float32),
    )


def make_state_and_net(num_actions=3, obs_shape=(12,), hidden=(32, 32), seed=0):
    net = DuelingMLP(num_actions=num_actions, hidden_sizes=hidden)
    opt = make_optimizer("adam", learning_rate=1e-3)
    state = init_train_state(
        net, opt, jax.random.PRNGKey(seed), jnp.zeros((1, *obs_shape), jnp.uint8)
    )
    return net, opt, state


def test_eight_virtual_devices_present():
    assert len(jax.devices()) == 8


def test_mesh_shapes():
    mesh = make_mesh()
    assert mesh.shape == {"data": 8, "model": 1}
    mesh = make_mesh(model_parallel=2)
    assert mesh.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        make_mesh(num_devices=6, model_parallel=4)
    with pytest.raises(ValueError):
        make_mesh(num_devices=16)


def test_dp_step_matches_single_device():
    """The mesh-sharded step must be numerically equivalent to the
    single-device fused step (same params, same batch)."""
    net, opt, state = make_state_and_net()
    batch = make_batch(32)

    single_step = build_train_step(net, opt, target_sync_freq=10)
    s1, m1 = single_step(state, jax.device_put(batch))

    _, _, state2 = make_state_and_net()  # fresh, identical init (same seed)
    mesh = make_mesh()
    dp_step, sharded_state = build_sharded_train_step(
        net, opt, mesh, state2, batch, target_sync_freq=10
    )
    s2, m2 = dp_step(sharded_state, place_batch(batch, mesh))

    assert np.isclose(float(m1.loss), float(m2.loss), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(m1.priorities), np.asarray(m2.priorities), rtol=1e-4, atol=1e-5
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(s1.params), jax.tree_util.tree_leaves(s2.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_dp_multiple_steps_stay_in_sync():
    net, opt, state = make_state_and_net()
    mesh = make_mesh()
    batch = make_batch(64)
    dp_step, sharded_state = build_sharded_train_step(net, opt, mesh, state, batch)
    for i in range(5):
        sharded_state, metrics = dp_step(
            sharded_state, place_batch(make_batch(64, seed=i), mesh)
        )
    assert int(sharded_state.step) == 5
    assert np.isfinite(float(metrics.loss))
    # Replicated leaves really are replicated (one shard each device).
    leaf = jax.tree_util.tree_leaves(sharded_state.params)[0]
    assert leaf.sharding.is_fully_replicated


def test_model_axis_shards_wide_kernels():
    net, opt, state = make_state_and_net(hidden=(512, 512))
    mesh = make_mesh(model_parallel=2)
    shardings = infer_param_sharding(state.params, mesh)
    specs = {
        path[-2].key if len(path) >= 2 else str(path): sh.spec
        for (path, sh) in jax.tree_util.tree_leaves_with_path(shardings)
    }
    # At least one wide dense kernel sharded over the model axis.
    assert any(spec == P(None, "model") for spec in specs.values()), specs
    # Train step still runs and matches the replicated result.
    batch = make_batch(32)
    dp_step, sharded_state = build_sharded_train_step(net, opt, mesh, state, batch)
    s2, m2 = dp_step(sharded_state, place_batch(batch, mesh))
    single = build_train_step(net, opt)
    _, _, state_b = make_state_and_net(hidden=(512, 512))
    s1, m1 = single(state_b, jax.device_put(batch))
    assert np.isclose(float(m1.loss), float(m2.loss), rtol=1e-4)


def test_conv_network_dp_step():
    """The flagship conv net through the sharded step on a 2D mesh."""
    net = build_network("conv", 4)
    opt = make_optimizer("rmsprop")
    obs_shape = (84, 84, 1)
    state = init_train_state(
        net, opt, jax.random.PRNGKey(0), jnp.zeros((1, *obs_shape), jnp.uint8)
    )
    mesh = make_mesh(model_parallel=2)
    batch = make_batch(16, obs_shape=obs_shape, num_actions=4)
    dp_step, sharded_state = build_sharded_train_step(net, opt, mesh, state, batch)
    new_state, metrics = dp_step(sharded_state, place_batch(batch, mesh))
    assert np.isfinite(float(metrics.loss))
    assert int(new_state.step) == 1


def test_async_pipeline_data_parallel_end_to_end():
    """learner.data_parallel=4 runs the WHOLE async runtime — actor thread,
    host replay, prefetch infeed, sharded train step, priority write-back,
    param publish — over a 4-device mesh (VERDICT r2 item 4)."""
    from ape_x_dqn_tpu.config import ApexConfig
    from ape_x_dqn_tpu.runtime.async_pipeline import AsyncPipeline

    cfg = ApexConfig()
    cfg.network = "mlp"
    cfg.env.name = "chain:6"
    cfg.actor.num_actors = 4
    cfg.actor.T = 100_000
    cfg.actor.flush_every = 8
    cfg.actor.sync_every = 16
    cfg.learner.data_parallel = 4
    cfg.learner.min_replay_mem_size = 128
    cfg.learner.publish_every = 10
    cfg.learner.optimizer = "adam"
    cfg.replay.capacity = 4096
    pipe = AsyncPipeline(cfg, log_every=100)
    assert pipe.mesh is not None and pipe.mesh.shape["data"] == 4
    # The live train state is actually sharded over the mesh.
    leaf = jax.tree_util.tree_leaves(pipe.comps.state.params)[0]
    assert len(leaf.sharding.device_set) == 4
    result = pipe.run(learner_steps=120, warmup_timeout=120.0)
    assert result["step"] >= 120
    assert np.isfinite(result["learner/loss"])  # key must exist: NaN fails
    assert result["param_version"] > 1
    # Priorities made it back from the sharded step into the host replay.
    assert pipe.comps.replay.size() > 0
