"""Checkpoint save/resume of the full train state + replay (SURVEY §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ape_x_dqn_tpu.learner.train_step import (
    build_train_step,
    init_train_state,
    make_optimizer,
)
from ape_x_dqn_tpu.models.dueling import DuelingMLP
from ape_x_dqn_tpu.replay import PrioritizedReplay
from ape_x_dqn_tpu.types import NStepTransition, PrioritizedBatch
from ape_x_dqn_tpu.utils.checkpoint import (
    latest_step,
    restore_checkpoint,
    save_checkpoint,
)


def make_state(seed=0):
    net = DuelingMLP(num_actions=3, hidden_sizes=(16,))
    opt = make_optimizer("adam", learning_rate=1e-3)
    state = init_train_state(
        net, opt, jax.random.PRNGKey(seed), jnp.zeros((1, 8), jnp.uint8)
    )
    return net, opt, state


def make_batch(B=16, seed=0):
    r = np.random.default_rng(seed)
    return PrioritizedBatch(
        transition=NStepTransition(
            obs=r.integers(0, 255, (B, 8), dtype=np.uint8),
            action=r.integers(0, 3, (B,), dtype=np.int32),
            reward=r.normal(size=(B,)).astype(np.float32),
            discount=np.full((B,), 0.9, np.float32),
            next_obs=r.integers(0, 255, (B, 8), dtype=np.uint8),
        ),
        indices=np.arange(B, dtype=np.int32),
        is_weights=np.ones((B,), np.float32),
    )


def test_roundtrip_full_state(tmp_path):
    net, opt, state = make_state()
    step_fn = build_train_step(net, opt)
    for i in range(3):
        state, _ = step_fn(state, jax.device_put(make_batch(seed=i)))
    save_checkpoint(str(tmp_path), state)
    assert latest_step(str(tmp_path)) == 3

    _, _, template = make_state(seed=99)  # different init
    restored, step = restore_checkpoint(str(tmp_path), template)
    assert step == 3
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(state)),
        jax.tree_util.tree_leaves(jax.device_get(restored)),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_resume_training_continues(tmp_path):
    """Optimizer state must survive: one more step after restore must equal
    the uninterrupted run bit-for-bit (same batches, same donation-free
    comparison)."""
    net, opt, state = make_state()
    step_fn = build_train_step(net, opt, jit=False)  # no donation: keep states
    s = state
    for i in range(2):
        s, _ = step_fn(s, jax.device_put(make_batch(seed=i)))
    save_checkpoint(str(tmp_path), s)
    s_cont, _ = step_fn(s, jax.device_put(make_batch(seed=7)))

    _, _, template = make_state(seed=5)
    restored, _ = restore_checkpoint(str(tmp_path), template)
    s_rest, _ = step_fn(restored, jax.device_put(make_batch(seed=7)))
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(s_cont.params)),
        jax.tree_util.tree_leaves(jax.device_get(s_rest.params)),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_replay_snapshot_roundtrip(tmp_path):
    _, _, state = make_state()
    rep = PrioritizedReplay(64, (8,))
    b = make_batch(20)
    rep.add(np.abs(np.random.default_rng(0).normal(size=20)) + 0.1, b.transition)
    save_checkpoint(str(tmp_path), state, replay=rep)

    rep2 = PrioritizedReplay(64, (8,))
    _, _, template = make_state(seed=1)
    restore_checkpoint(str(tmp_path), template, replay=rep2)
    assert rep2.size() == 20
    assert np.isclose(rep2._tree.total, rep._tree.total)


def test_keep_prunes_old(tmp_path):
    net, opt, state = make_state()
    step_fn = build_train_step(net, opt)
    for i in range(5):
        state, _ = step_fn(state, jax.device_put(make_batch(seed=i)))
        save_checkpoint(str(tmp_path), state, keep=2)
    import os

    kept = sorted(d for d in os.listdir(tmp_path) if d.startswith("step_"))
    assert kept == ["step_4", "step_5"]


def test_missing_checkpoint_raises(tmp_path):
    _, _, template = make_state()
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(str(tmp_path / "nope"), template)


def test_driver_restore_gate(tmp_path):
    """The config-gated resume path (reference learner.py:18-23 semantics)."""
    from ape_x_dqn_tpu.config import ApexConfig
    from ape_x_dqn_tpu.runtime.single_process import SingleProcessDriver

    def cfg():
        c = ApexConfig()
        c.env.name = "chain:6"
        c.network = "mlp"
        c.actor.num_actors = 2
        c.actor.flush_every = 4
        c.learner.min_replay_mem_size = 64
        c.replay.capacity = 1000
        c.learner.checkpoint_every = 10
        c.learner.checkpoint_dir = str(tmp_path)
        return c.validate()

    d1 = SingleProcessDriver(cfg())
    d1.run(learner_steps=10)
    assert latest_step(str(tmp_path)) == 10

    c2 = cfg()
    c2.learner.restore_from = str(tmp_path)
    d2 = SingleProcessDriver(c2)
    assert d2.learner_step == 10  # resumed, not fresh

    # Missing path falls back to scratch with a warning, like the reference.
    c3 = cfg()
    c3.learner.restore_from = str(tmp_path / "missing")
    d3 = SingleProcessDriver(c3)
    assert d3.learner_step == 0


def test_async_pipeline_kill_and_resume(tmp_path):
    """VERDICT r2 item 6: train, checkpoint, then a NEW pipeline resumes —
    learner step AND replay contents both survive the restart."""
    from ape_x_dqn_tpu.config import ApexConfig
    from ape_x_dqn_tpu.runtime.async_pipeline import AsyncPipeline

    def make_cfg():
        cfg = ApexConfig()
        cfg.network = "mlp"
        cfg.env.name = "chain:6"
        cfg.actor.num_actors = 2
        cfg.actor.T = 100_000
        cfg.actor.flush_every = 8
        cfg.actor.sync_every = 16
        cfg.learner.min_replay_mem_size = 128
        cfg.learner.optimizer = "adam"
        cfg.learner.checkpoint_every = 50
        cfg.learner.checkpoint_dir = str(tmp_path / "ckpt")
        cfg.replay.capacity = 4096
        return cfg

    pipe1 = AsyncPipeline(make_cfg(), log_every=100)
    pipe1.run(learner_steps=100, warmup_timeout=120.0)
    saved_size = pipe1.comps.replay.size()
    assert saved_size > 0

    cfg2 = make_cfg()
    cfg2.learner.restore_from = True  # "my checkpoint_dir"
    pipe2 = AsyncPipeline(cfg2, log_every=100)
    # Both the step counter and the buffer crossed the process boundary.
    assert pipe2.comps.learner_step == 100
    assert pipe2.learner_step == 100
    restored_size = pipe2.comps.replay.size()
    assert 0 < restored_size <= saved_size  # saved at the step-100 checkpoint
    # And training continues from there rather than restarting.
    result = pipe2.run(learner_steps=150, warmup_timeout=120.0)
    assert result["step"] >= 150


def test_fused_learner_replay_snapshot_roundtrip(tmp_path):
    """Device-replay (HBM ring) checkpoint leg: save via save_checkpoint
    (replay=fused learner), restore via load_replay_snapshot."""
    from ape_x_dqn_tpu.runtime.fused_learner import FusedDeviceLearner
    from ape_x_dqn_tpu.utils.checkpoint import load_replay_snapshot

    net = DuelingMLP(num_actions=3, hidden_sizes=(16,))
    opt = make_optimizer("adam", learning_rate=1e-3)

    def make_fused():
        state = init_train_state(net, opt, jax.random.PRNGKey(0),
                                 jnp.zeros((1, 8), jnp.uint8))
        return FusedDeviceLearner(
            net, opt, state, (8,), capacity=128, batch_size=16,
            steps_per_call=4, ingest_block=32, target_sync_freq=8,
        )

    fused = make_fused()
    r = np.random.default_rng(0)
    M = 64
    fused.add_chunk(
        np.abs(r.normal(size=M)).astype(np.float32) + 0.1,
        NStepTransition(
            obs=r.integers(0, 255, (M, 8), dtype=np.uint8),
            action=r.integers(0, 3, (M,), dtype=np.int32),
            reward=r.normal(size=(M,)).astype(np.float32),
            discount=np.full((M,), 0.9, np.float32),
            next_obs=r.integers(0, 255, (M, 8), dtype=np.uint8),
        ),
    )
    fused.ingest_staged()
    fused.train(beta=0.4)
    path = save_checkpoint(str(tmp_path), fused.state, replay=fused)
    assert "replay.npz" in str(list(__import__("os").listdir(path)))

    fused2 = make_fused()
    assert load_replay_snapshot(str(tmp_path), fused2)
    assert fused2.size == fused.size
    np.testing.assert_array_equal(
        np.asarray(fused2._replay.mass), np.asarray(fused._replay.mass)
    )
    np.testing.assert_array_equal(
        np.asarray(fused2._replay.obs), np.asarray(fused._replay.obs)
    )
    # Restored ring trains immediately.
    metrics = fused2.train(beta=0.4)
    assert np.isfinite(np.asarray(metrics.loss)).all()


def test_periodic_fused_checkpoint_includes_staged_rows(tmp_path):
    """Round-3 verdict weak item 6: the periodic fused-mode save must drain
    staged-but-uningested host rows into the ring first, so a crash-restore
    from that checkpoint loses no experience."""
    from ape_x_dqn_tpu.config import ApexConfig
    from ape_x_dqn_tpu.runtime.async_pipeline import AsyncPipeline
    from ape_x_dqn_tpu.runtime.fused_learner import FusedDeviceLearner
    from ape_x_dqn_tpu.utils.checkpoint import load_replay_snapshot

    cfg = ApexConfig()
    cfg.env.name = "chain:6"
    cfg.network = "mlp"
    cfg.learner.device_replay = True
    cfg.learner.steps_per_call = 4
    cfg.learner.replay_sample_size = 16
    cfg.learner.checkpoint_every = 4
    cfg.learner.checkpoint_dir = str(tmp_path)
    cfg.learner.min_replay_mem_size = 64
    cfg.replay.capacity = 256
    cfg.validate()
    pipe = AsyncPipeline(cfg)  # actors never started — driven by hand

    def chunk(M, seed):
        rr = np.random.default_rng(seed)
        return NStepTransition(
            obs=rr.integers(0, 255, (M, 6), dtype=np.uint8),
            action=rr.integers(0, 2, (M,), dtype=np.int32),
            reward=rr.normal(size=(M,)).astype(np.float32),
            discount=np.full((M,), 0.9, np.float32),
            next_obs=rr.integers(0, 255, (M, 6), dtype=np.uint8),
        )

    # 40 rows staged with ingest_block (256 default) > 40: a naive save
    # would snapshot an empty ring and lose them all.
    pipe.fused.add_chunk(np.ones(40, np.float32), chunk(40, 1))
    pipe.fused.ingest_staged()  # no full block → nothing lands
    assert pipe.fused.staged_rows == 40 and pipe.fused.size == 0
    path = pipe._save_fused_checkpoint()

    # Restore into a fresh ring: every staged row must be present.
    state2 = init_train_state(
        pipe.comps.network, pipe.comps.optimizer, jax.random.PRNGKey(9),
        jnp.zeros((1, 6), jnp.uint8),
    )
    fused2 = FusedDeviceLearner(
        pipe.comps.network, pipe.comps.optimizer, state2, (6,),
        capacity=256, batch_size=16, steps_per_call=4,
    )
    assert load_replay_snapshot(path, fused2)
    assert fused2.size == 40


def test_load_replay_snapshot_absent_returns_false(tmp_path):
    net = DuelingMLP(num_actions=3, hidden_sizes=(16,))
    opt = make_optimizer("adam")
    state = init_train_state(net, opt, jax.random.PRNGKey(0),
                             jnp.zeros((1, 8), jnp.uint8))
    save_checkpoint(str(tmp_path), state)  # no replay leg
    from ape_x_dqn_tpu.utils.checkpoint import load_replay_snapshot

    class Sink:
        def load_state_dict(self, d):
            raise AssertionError("must not be called")

    assert load_replay_snapshot(str(tmp_path), Sink()) is False


def test_per_host_replay_shards_roundtrip(tmp_path):
    """Multi-host checkpoint layout: process 0 saves state + its shard,
    other hosts save replay-only shards into the same step dir; each host
    restores ITS OWN shard (nothing lost, nothing duplicated)."""
    from ape_x_dqn_tpu.utils.checkpoint import (
        load_replay_snapshot,
        save_replay_snapshot,
    )

    net = DuelingMLP(num_actions=3, hidden_sizes=(16,))
    opt = make_optimizer("adam")
    state = init_train_state(net, opt, jax.random.PRNGKey(0),
                             jnp.zeros((1, 8), jnp.uint8))

    def filled_replay(fill_value):
        rep = PrioritizedReplay(64, (8,))
        n = 16
        rep.add(
            np.full(n, 1.0),
            NStepTransition(
                obs=np.full((n, 8), fill_value, np.uint8),
                action=np.zeros(n, np.int32),
                reward=np.ones(n, np.float32),
                discount=np.full(n, 0.9, np.float32),
                next_obs=np.full((n, 8), fill_value, np.uint8),
            ),
        )
        return rep

    r0, r1 = filled_replay(11), filled_replay(22)
    # Host 0 writes state + its shard; host 1 its shard only.
    save_checkpoint(str(tmp_path), state, replay=r0, replay_suffix="_h0")
    save_replay_snapshot(str(tmp_path), int(state.step), r1,
                         replay_suffix="_h1")
    # Each host restores its own shard.
    back0, back1 = PrioritizedReplay(64, (8,)), PrioritizedReplay(64, (8,))
    assert load_replay_snapshot(str(tmp_path), back0, replay_suffix="_h0")
    assert load_replay_snapshot(str(tmp_path), back1, replay_suffix="_h1")
    assert back0._obs.get(np.arange(1))[0, 0] == 11
    assert back1._obs.get(np.arange(1))[0, 0] == 22
    # The wrong suffix is absent, not silently cross-loaded.
    assert not load_replay_snapshot(str(tmp_path), PrioritizedReplay(64, (8,)),
                                    replay_suffix="_h9")
