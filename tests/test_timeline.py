"""Flight-data recorder (ISSUE 19): the on-disk timeline store's
framing/commit/adopt discipline, delta compaction that stays
bit-consistent with the live ``_BucketWindow`` rollup, the SLO
burn-window rebuild that kills the post-respawn blind window, the
concurrent scrape plane's cadence under a hung endpoint, per-version
serving telemetry, Prometheus exposition correctness, and the
``obs_diff`` run-vs-run regression report."""

from __future__ import annotations

import json
import os
import struct
import threading
import time

import pytest

from ape_x_dqn_tpu.obs.fleet import (
    FleetAggregator,
    SloEngine,
    SloRule,
    _BucketWindow,
    _endpoints_down,
)
from ape_x_dqn_tpu.obs.timeline import (
    TimelineStore,
    read_segment,
    read_timeline,
)
from ape_x_dqn_tpu.runtime.net import TIMELINE_MAGIC
from ape_x_dqn_tpu.utils.metrics import bucket_percentile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _doc_keys(section_header):
    from ape_x_dqn_tpu.analysis.metrics_doc import doc_section_keys

    return doc_section_keys(
        section_header, os.path.join(REPO, "docs", "METRICS.md"))


def _rollup(i, *, buckets=None, alive=5, down=0):
    """A minimal fleet rollup for sweep ``i`` with cumulative counters."""
    buckets = buckets if buckets is not None else {
        "0.001": 3 * (i + 1), "0.01": i + 1}
    return {
        "alive": alive, "expected": alive + down,
        "endpoints": {
            f"ep{j}": {"alive": j >= down} for j in range(alive + down)
        },
        "scrapes": 5 * (i + 1), "scrape_failures": down * (i + 1),
        "serving": {"replicas": 2, "count": sum(buckets.values()),
                    "qps": 10.0, "latency_buckets": dict(buckets),
                    "window": {"count": 1, "p99_ms": 1.0},
                    "exemplars": {"0.001": 1000 + i}},
        "replay": {"shards_alive": 2, "total_added": 11 * (i + 1),
                   "add_qps": 11.0, "occupancy": 0.25,
                   "op_buckets": {"0.001": 11 * (i + 1)},
                   "op_exemplars": {"0.001": 2000 + i}},
        "age_of_experience": {"count": 4 * (i + 1),
                              "buckets_s": {"0.1": 4 * (i + 1)},
                              "window": {"count": 4, "p95_s": 0.1}},
        "inference": {"rtt_exemplars": {"0.01": 3000 + i}},
        "ring_occupancy_max": 0.5,
    }


class TestTimelineStore:
    def test_append_compacts_deltas_and_roundtrips(self, tmp_path):
        st = TimelineStore(str(tmp_path))
        for i in range(3):
            st.append_sweep(_rollup(i), now=100.0 + i)
        st.close()
        doc = read_timeline(str(tmp_path))
        assert doc["torn"] == 0 and len(doc["records"]) == 3
        r0, r1, _ = doc["records"]
        # First sweep's delta is the full cumulative; later sweeps store
        # only the per-sweep increment.
        assert r0["counters"]["replay_added"] == 11
        assert r1["counters"]["replay_added"] == 11
        assert r0["hist"]["serving_s"] == {"0.001": 3, "0.01": 1}
        assert r1["hist"]["serving_s"] == {"0.001": 3, "0.01": 1}
        assert r1["gauges"]["alive"] == 5
        assert r1["exemplars"]["replay_op"] == {"0.001": 2001}

    def test_records_carry_registered_magic(self, tmp_path):
        st = TimelineStore(str(tmp_path), compress=False)
        st.append_sweep(_rollup(0), now=1.0)
        st.close()
        seg = sorted(p for p in os.listdir(tmp_path)
                     if p.endswith(".seg"))[0]
        with open(tmp_path / seg, "rb") as f:
            assert f.read(4) == TIMELINE_MAGIC

    def test_torn_tail_dropped_at_frame_boundary(self, tmp_path):
        st = TimelineStore(str(tmp_path), compress=False)
        for i in range(4):
            st.append_sweep(_rollup(i), now=10.0 + i)
        path = st._active_path()
        st.close()
        # A SIGKILL mid-write leaves a half-frame: truncate the (now
        # committed) segment mid-record and re-read.
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(size - 7)
        recs, torn = read_segment(path)
        assert len(recs) == 3 and torn == 1
        # Corruption inside a payload (CRC mismatch) also stops the read
        # at the last good frame instead of decoding garbage.
        with open(path, "r+b") as f:
            f.seek(20)
            f.write(b"\xff")
        recs2, torn2 = read_segment(path)
        assert len(recs2) < 3 and torn2 >= 1

    def test_unclean_shutdown_tail_is_adopted(self, tmp_path):
        st = TimelineStore(str(tmp_path))
        for i in range(5):
            st.append_sweep(_rollup(i), now=50.0 + i)
        # NO close(): the active segment is an uncommitted orphan.
        del st
        st2 = TimelineStore(str(tmp_path))
        assert st2.adopted_records == 5
        assert len(st2.records()) == 5
        # Delta marks resume from the adopted tail's cumulative echo —
        # the next sweep must NOT double-count the whole run.
        st2.append_sweep(_rollup(5), now=55.0)
        last = st2.records()[-1]
        assert last["counters"]["replay_added"] == 11
        assert last["hist"]["serving_s"] == {"0.001": 3, "0.01": 1}
        st2.close()

    def test_rotation_and_generation_pruning_bound_disk(self, tmp_path):
        st = TimelineStore(str(tmp_path), max_bytes=8192,
                           segment_bytes=2048, compress=False)
        for i in range(200):
            st.append_sweep(_rollup(i), now=1000.0 + i)
        assert st.rotations > 0 and st.prunes > 0
        total = sum(
            os.path.getsize(tmp_path / p) for p in os.listdir(tmp_path)
            if p.endswith(".seg"))
        # Bounded: committed segments respect max_bytes; the active
        # segment can overshoot by at most one segment's worth.
        assert total <= 8192 + 2048
        # Oldest generations are gone, newest survive, in order.
        doc = read_timeline(str(tmp_path))
        ts = [r["t"] for r in doc["records"]]
        assert ts == sorted(ts) and ts[0] > 1000.0
        st.close()

    def test_windowed_percentile_bit_consistent_with_live_window(
            self, tmp_path):
        st = TimelineStore(str(tmp_path))
        win = _BucketWindow(window_s=60.0)
        cum = {}
        for i in range(30):
            # A drifting cumulative distribution.
            cum = {"0.001": 5 * (i + 1), "0.01": 2 * (i + 1),
                   "0.1": i // 3}
            now = 500.0 + i * 0.3
            win.feed(cum, now)
            st.append_sweep(_rollup(i, buckets=cum), now=now)
        t1 = 500.0 + 29 * 0.3
        for q in (50, 90, 99):
            assert st.percentile("serving_s", q, t1 - 60.0, t1) \
                == win.percentile(q)
        # And an arbitrary sub-window re-aggregates consistently.
        mid = st.merged_buckets("serving_s", 502.0, 505.0)
        assert st.percentile("serving_s", 99, 502.0, 505.0) \
            == bucket_percentile(mid, 99)
        st.close()

    def test_rate_windows(self, tmp_path):
        st = TimelineStore(str(tmp_path))
        for i in range(10):
            st.append_sweep(_rollup(i), now=100.0 + i)
        # 11 adds/sweep, 1s apart: records at t in [104, 109] carry
        # 6 deltas of 11 over a 5s window.
        assert st.rate("replay_added", 5.0, now=109.0) \
            == pytest.approx(66 / 5.0)
        # A key the fleet never reported rates 0 (covered but silent);
        # a window past the stored span has no coverage at all.
        assert st.rate("nonexistent", 5.0, now=109.0) == 0.0
        assert st.rate("replay_added", 5.0, now=200.0) is None
        st.close()
        empty = TimelineStore(str(tmp_path / "empty"))
        assert empty.rate("replay_added", 5.0) is None
        empty.close()

    def test_exemplar_lookup_newest_and_by_bucket(self, tmp_path):
        st = TimelineStore(str(tmp_path))
        for i in range(4):
            st.append_sweep(_rollup(i), now=10.0 + i)
        assert st.exemplar("replay_op") == 2003
        assert st.exemplar("replay_op", edge="0.001") == 2003
        assert st.exemplar("serving", edge="0.001") == 1003
        assert st.exemplar("serving", edge="99") is None
        st.close()

    def test_stats_match_doc(self, tmp_path):
        st = TimelineStore(str(tmp_path))
        st.append_sweep(_rollup(0), now=1.0)
        assert set(st.stats()) == set(_doc_keys("## Timeline schema"))
        st.close()

    def test_bad_geometry_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            TimelineStore(str(tmp_path), max_bytes=10, segment_bytes=20)


class TestSloRebuild:
    def _engine(self, events):
        return SloEngine(
            [SloRule("endpoints_alive", "upper", 0.0, _endpoints_down)],
            window_s=8.0, burn_threshold=0.5, clear_threshold=0.1,
            min_samples=3,
            emit=lambda name, **f: events.append(name),
        )

    def test_rebuild_restores_breach_without_events(self, tmp_path):
        st = TimelineStore(str(tmp_path))
        ev1: list = []
        eng1 = self._engine(ev1)
        now = 100.0
        for i in range(6):
            roll = _rollup(i, alive=4, down=1)
            status = eng1.evaluate(roll, now=now)
            st.append_sweep(roll, status, now=now)
            now += 0.5
        assert eng1.rules[0].state == "breach" and ev1 == ["slo_breach"]
        del st      # SIGKILL-equivalent: no close

        ev2: list = []
        eng2 = self._engine(ev2)
        st2 = TimelineStore(str(tmp_path))
        filled = st2.rebuild_slo(eng2, now=now)
        # The cold engine comes back already in breach, window refilled,
        # with NO breach/clear emitted during the rebuild itself.
        assert filled == 1 and ev2 == []
        rule = eng2.rules[0]
        assert rule.state == "breach" and len(rule._window) == 6
        assert st2.rebuilds == 1
        # The recovery clear then fires off the restored window — once
        # the old violated samples age out, not min_samples later.
        for _ in range(20):
            eng2.evaluate(_rollup(0, alive=5), now=now)
            now += 0.5
        assert ev2 == ["slo_clear"] and rule.state == "ok"
        st2.close()

    def test_rebuild_on_empty_timeline_is_noop(self, tmp_path):
        st = TimelineStore(str(tmp_path))
        ev: list = []
        eng = self._engine(ev)
        assert st.rebuild_slo(eng) == 0 and ev == []
        assert eng.rules[0].state == "ok"
        st.close()


class TestConcurrentScrape:
    def test_hung_endpoint_does_not_stretch_the_sweep(self):
        """The serial loop cost N x timeout per sweep once one endpoint
        wedged; the concurrent plane bounds the WHOLE cycle near one
        timeout, keeps scraping the healthy members, and refuses to
        stack workers behind the stuck one."""
        hang = threading.Event()
        calls = {"healthy": 0}

        def wedged():
            hang.wait(20.0)
            return {}

        def healthy():
            calls["healthy"] += 1
            return {"replay_service": {"requests": 1}}

        agg = FleetAggregator(scrape_timeout_s=0.5, scrape_workers=4)
        try:
            agg.add_local("stuck", wedged, kind="trainer")
            for i in range(3):
                agg.add_local(f"ok{i}", healthy, kind="trainer")
            t0 = time.monotonic()
            agg.scrape_once()
            first = time.monotonic() - t0
            assert first < 2.0          # one deadline, not 4 timeouts
            roll = agg.rollup()
            assert roll["alive"] == 3
            assert "ScrapeDeadline" in \
                roll["endpoints"]["stuck"]["last_error"]
            # Second sweep: the wedged future is still in flight — the
            # endpoint reports stuck instead of queueing another worker.
            t0 = time.monotonic()
            agg.scrape_once()
            assert time.monotonic() - t0 < 2.0
            assert "ScrapeStuck" in \
                agg.rollup()["endpoints"]["stuck"]["last_error"]
            assert calls["healthy"] == 6   # healthy members kept cadence
        finally:
            hang.set()
            agg.close()

    def test_attach_timeline_records_sweeps_and_lifts_windows(
            self, tmp_path):
        agg = FleetAggregator(scrape_timeout_s=1.0, window_s=30.0)
        try:
            agg.add_local(
                "shard0",
                lambda: {"requests": 5, "total_added": 7, "size": 7,
                         "capacity": 100},
                kind="shard")
            st = TimelineStore(str(tmp_path))
            agg.attach_timeline(st)
            agg.scrape_once()
            time.sleep(0.05)
            agg.scrape_once()
            recs = st.records()
            assert len(recs) == 2
            assert recs[0]["gauges"]["alive"] == 1
            # The windowed replay add rate is lifted back INTO the
            # rollup for the autopilot's idle rules.
            rep = agg.rollup()["replay"]
            assert rep["window"]["add_qps"] >= 0.0
        finally:
            agg.close()


class TestPerVersionServing:
    def test_net_server_splits_stats_by_param_version(self):
        from ape_x_dqn_tpu.serving.net_server import ServingNetServer

        class _Stub:
            def infer(self, obs):
                raise NotImplementedError

        srv = ServingNetServer(_Stub())
        for v, dt in ((3, 0.001), (3, 0.002), (4, 0.1)):
            srv._record_reply(v, dt, trace_id=v * 10)
        stats = srv.stats()
        assert stats["by_version"]["3"]["replies"] == 2
        assert stats["by_version"]["4"]["replies"] == 1
        assert stats["by_version"]["4"]["latency"]["p50_ms"] \
            > stats["by_version"]["3"]["latency"]["p50_ms"]
        assert stats["by_version"]["3"]["latency_buckets"]
        # Exemplars: the newest trace id lands in the bucket its
        # latency resolves to.
        assert 40 in stats["latency_exemplars"].values()

    def test_version_rows_are_bounded(self):
        from ape_x_dqn_tpu.serving.net_server import ServingNetServer
        from ape_x_dqn_tpu.serving.net_server import _MAX_VERSIONS

        srv = ServingNetServer(object())
        for v in range(10):
            srv._record_reply(v, 0.001, trace_id=0)
        stats = srv.stats()
        assert len(stats["by_version"]) == _MAX_VERSIONS
        # Oldest versions evicted, newest kept.
        assert set(stats["by_version"]) == {"6", "7", "8", "9"}


class TestPrometheusExposition:
    def test_nan_and_inf_spellings(self):
        from ape_x_dqn_tpu.obs.registry import MetricsRegistry

        r = MetricsRegistry(prefix="apex")
        r.gauge("nan_g").set(float("nan"))
        r.gauge("inf_g").set(float("inf"))
        r.gauge("ninf_g").set(float("-inf"))
        text = r.prometheus_text()
        # The exposition format's exact spellings — not python's
        # str(float) forms ("nan"/"inf"), which scrapers reject.
        assert "apex_nan_g NaN" in text
        assert "apex_inf_g +Inf" in text
        assert "apex_ninf_g -Inf" in text
        assert "apex_nan_g nan" not in text

    def test_help_text_is_escaped(self):
        from ape_x_dqn_tpu.obs.registry import MetricsRegistry

        r = MetricsRegistry(prefix="apex")
        r.counter("c", help="line one\nline two \\ backslash").inc()
        text = r.prometheus_text()
        help_line = next(ln for ln in text.splitlines()
                         if ln.startswith("# HELP apex_c"))
        assert "\n" not in help_line
        assert "line one\\nline two \\\\ backslash" in help_line

    def test_summary_emits_sum_and_ordered_quantiles(self):
        from ape_x_dqn_tpu.obs.registry import MetricsRegistry

        r = MetricsRegistry(prefix="apex")
        h = r.histogram("lat")
        for dt in (0.001, 0.002, 0.005, 0.05):
            h.observe(dt)
        lines = r.prometheus_text().splitlines()
        qs = [float(ln.split()[-1]) for ln in lines
              if 'apex_lat{quantile=' in ln]
        assert qs == sorted(qs) and len(qs) == 3
        sum_line = next(ln for ln in lines
                        if ln.startswith("apex_lat_sum "))
        assert float(sum_line.split()[-1]) == pytest.approx(0.058)
        # _sum precedes _count (scrapers pair them within one family).
        assert lines.index(sum_line) < lines.index(
            next(ln for ln in lines if ln.startswith("apex_lat_count")))

    def test_provider_nan_leaf_is_spelled_not_crashed(self):
        from ape_x_dqn_tpu.obs.registry import MetricsRegistry

        r = MetricsRegistry(prefix="apex")
        r.register_provider("p", lambda: {"bad": float("nan"),
                                          "good": 1.0})
        text = r.prometheus_text()
        assert "apex_p_good 1" in text and "apex_p_bad NaN" in text

    def test_metrics_endpoint_serves_the_exposition(self):
        import urllib.request

        from ape_x_dqn_tpu.obs.exporter import ObsServer
        from ape_x_dqn_tpu.obs.registry import MetricsRegistry

        r = MetricsRegistry(prefix="apex")
        r.gauge("spiky").set(float("inf"))
        r.histogram("lat").observe(0.003)
        srv = ObsServer(r, port=0)
        try:
            with urllib.request.urlopen(
                    f"{srv.url}/metrics", timeout=5.0) as resp:
                assert resp.headers["Content-Type"].startswith(
                    "text/plain")
                text = resp.read().decode()
            assert "apex_spiky +Inf" in text
            assert "apex_lat_sum" in text and "apex_lat_count 1" in text
            assert text.endswith("\n")
        finally:
            srv.close()


class TestObsDiff:
    def _mk(self, tmp_path, name, n=10, lat_edge="0.001"):
        st = TimelineStore(str(tmp_path / name))
        for i in range(n):
            st.append_sweep(
                _rollup(i, buckets={lat_edge: 5 * (i + 1)}),
                {"rules": {"r": {"state": "ok", "kind": "upper",
                                 "bound": 0.0, "value": 0.0,
                                 "burn": 0.0, "samples": 5,
                                 "breaches": 0, "clears": 0}}},
                now=100.0 + i)
        st.close()
        return str(tmp_path / name)

    def test_diff_flags_latency_regression_only(self, tmp_path):
        sys_path_hack = REPO
        import sys
        if sys_path_hack not in sys.path:
            sys.path.insert(0, sys_path_hack)
        from tools import obs_diff

        a = self._mk(tmp_path, "a", lat_edge="0.001")
        b = self._mk(tmp_path, "b", lat_edge="0.1")
        report = obs_diff.diff(obs_diff.load_side(a),
                               obs_diff.load_side(b))
        assert not report["ok"]
        assert "serving_p99_ms" in report["regressions"]
        # Same run against itself: clean.
        self_report = obs_diff.diff(obs_diff.load_side(a),
                                    obs_diff.load_side(a))
        assert self_report["ok"] and not self_report["regressions"]
        assert "serving_p99_ms" in [r["metric"]
                                    for r in self_report["rows"]]

    def test_load_side_accepts_demo_artifact_wrapper(self, tmp_path):
        import sys
        if REPO not in sys.path:
            sys.path.insert(0, REPO)
        from tools import obs_diff

        a = self._mk(tmp_path, "a")
        summary = obs_diff.load_side(a)
        demo = tmp_path / "demo.json"
        demo.write_text(json.dumps({"ok": True,
                                    "timeline_summary": summary}))
        assert obs_diff.load_side(str(demo)) == summary
        with pytest.raises(ValueError):
            bad = tmp_path / "bad.json"
            bad.write_text(json.dumps({"unrelated": 1}))
            obs_diff.load_side(str(bad))

    def test_render_is_line_oriented(self, tmp_path):
        import sys
        if REPO not in sys.path:
            sys.path.insert(0, REPO)
        from tools import obs_diff

        a = self._mk(tmp_path, "a")
        report = obs_diff.diff(obs_diff.load_side(a),
                               obs_diff.load_side(a))
        out = obs_diff.render(report)
        assert out.splitlines()[0].startswith("== obs_diff ==")
        assert "OK" in out.splitlines()[0]
