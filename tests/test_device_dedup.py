"""Device frame-dedup ring: gather correctness, wrap-aware liveness, and
the fused-step oracle against the double-store layout (verdict item 1a,
device leg)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ape_x_dqn_tpu.replay.device import (
    build_fused_learn_step,
    device_replay_add,
    init_device_replay,
)
from ape_x_dqn_tpu.replay.device_dedup import (
    build_dedup_fused_learn_step,
    dedup_device_add_frames,
    dedup_device_add_transitions,
    dedup_sample_many,
    init_dedup_device_replay,
)
from ape_x_dqn_tpu.types import NStepTransition

OBS = (4, 4, 1)


def frame(seq: int) -> np.ndarray:
    return np.full(OBS, seq % 251, np.uint8)


def make_stream(n_chunks=6, n_tx=8, seed=0):
    """Paired ingest streams: dedup (frames + abs refs) and the dense
    NStepTransition materialization, content-identical by construction.
    Chunk i contributes n_tx transitions over n_tx+1 fresh frames, with
    obs_i = frame(base+i), next_i = frame(base+i+1)."""
    rng = np.random.default_rng(seed)
    dedup, dense, prios = [], [], []
    fbase = 0
    for _ in range(n_chunks):
        U = n_tx + 1
        frames = np.stack([frame(fbase + i) for i in range(U)])
        obs_ref = fbase + np.arange(n_tx)
        next_ref = fbase + 1 + np.arange(n_tx)
        action = rng.integers(0, 3, n_tx).astype(np.int32)
        reward = rng.normal(size=n_tx).astype(np.float32)
        discount = np.full(n_tx, 0.97, np.float32)
        p = (np.abs(rng.normal(size=n_tx)) + 0.1).astype(np.float32)
        dedup.append((frames, obs_ref, next_ref, action, reward, discount))
        dense.append(NStepTransition(
            obs=np.stack([frame(s) for s in obs_ref]),
            action=action, reward=reward, discount=discount,
            next_obs=np.stack([frame(s) for s in next_ref]),
        ))
        prios.append(p)
        fbase += U
    return dedup, dense, prios


def ingest_dedup(state, stream, prios, start=0, modulus=None):
    add_f = jax.jit(dedup_device_add_frames, donate_argnums=(0,))
    add_t = jax.jit(dedup_device_add_transitions, donate_argnums=(0,))
    Q = modulus or state.seq_modulus
    for (frames, oref, nref, a, r, d), p in zip(stream[start:], prios[start:]):
        state = add_f(state, jnp.asarray(frames))
        state = add_t(
            state,
            jnp.asarray(oref % Q, jnp.int32), jnp.asarray(nref % Q, jnp.int32),
            jnp.asarray(a), jnp.asarray(r), jnp.asarray(d), jnp.asarray(p),
        )
    return state


class TestDedupRing:
    def test_gather_matches_refs(self):
        dedup, dense, prios = make_stream()
        st = init_dedup_device_replay(64, OBS, frame_capacity=64)
        st = ingest_dedup(st, dedup, prios)
        batch = jax.tree_util.tree_map(
            lambda a: a[0],
            dedup_sample_many(st, jax.random.PRNGKey(0), 1, 16),
        )
        idx = np.asarray(batch.indices)
        oref = np.asarray(st.obs_ref)[idx]
        nref = np.asarray(st.next_ref)[idx]
        np.testing.assert_array_equal(
            np.asarray(batch.transition.obs), np.stack([frame(s) for s in oref])
        )
        np.testing.assert_array_equal(
            np.asarray(batch.transition.next_obs),
            np.stack([frame(s) for s in nref]),
        )

    def test_frame_death_sweep(self):
        """Frame ring smaller than the arrival stream: the oldest rows'
        masses go to zero in the same ingest that overwrites their frames."""
        dedup, _, prios = make_stream(n_chunks=8, n_tx=8)
        # 8 chunks x 9 frames = 72 frames > Cf=32: early chunks age out.
        st = init_dedup_device_replay(64, OBS, frame_capacity=32)
        st = ingest_dedup(st, dedup, prios)
        mass = np.asarray(st.mass)
        age = (int(st.fcount) - np.asarray(st.obs_ref)) % st.seq_modulus
        rows = np.arange(48)  # 48 rows written, ring not yet wrapped
        dead = age[rows] > 32
        assert dead.any() and (~dead).any()
        assert (mass[rows][dead] == 0).all()
        assert (mass[rows][~dead] > 0).all()

    def test_seq_wrap_is_transparent(self):
        """Start the frame counter just below the modulus Q: ingest crosses
        the int32-safe wrap and sampling still gathers the right frames."""
        dedup, _, prios = make_stream(n_chunks=4, n_tx=8)
        st = init_dedup_device_replay(64, OBS, frame_capacity=32)
        Q = st.seq_modulus
        start = Q - 17  # wraps mid-stream
        st = st.replace(fcount=jnp.int32(start))
        shifted = [
            (f, (o + start) % Q, (n + start) % Q, a, r, d)
            for f, o, n, a, r, d in dedup
        ]
        st = ingest_dedup(st, shifted, prios, modulus=Q)
        assert int(st.fcount) == (start + 4 * 9) % Q
        batch = jax.tree_util.tree_map(
            lambda a: a[0],
            dedup_sample_many(st, jax.random.PRNGKey(1), 1, 16),
        )
        idx = np.asarray(batch.indices)
        # Recover the pre-shift seq to predict content.
        oref = (np.asarray(st.obs_ref)[idx] - start) % Q
        np.testing.assert_array_equal(
            np.asarray(batch.transition.obs), np.stack([frame(s) for s in oref])
        )

    def test_footprint_observable(self):
        dd = init_dedup_device_replay(1024, OBS, frame_ratio=1.25)
        ds = init_device_replay(1024, OBS)
        frames_dd = dd.frames.nbytes
        frames_ds = ds.obs.nbytes + ds.next_obs.nbytes
        assert frames_dd == pytest.approx(0.625 * frames_ds, rel=0.01)


def build_learner(seed=0):
    from ape_x_dqn_tpu.learner.train_step import (
        build_train_step,
        init_train_state,
        make_optimizer,
    )
    from ape_x_dqn_tpu.models.dueling import DuelingMLP

    net = DuelingMLP(num_actions=3, hidden_sizes=(16,))
    opt = make_optimizer("adam", learning_rate=1e-3)
    state = init_train_state(
        net, opt, jax.random.PRNGKey(seed),
        np.zeros((1, *OBS), np.uint8),
    )
    step_fn = build_train_step(net, opt, sync_in_step=False, jit=False)
    return state, step_fn


class TestFusedOracle:
    @pytest.mark.parametrize("sample_ahead", [False, True])
    def test_dedup_fused_equals_double_store_fused(self, sample_ahead):
        """The money test: identical content ingested into both layouts,
        identical rng → the K-step fused scan must produce identical
        params, metrics, and post-restamp masses."""
        dedup, dense, prios = make_stream(n_chunks=6, n_tx=8)
        C = 64
        dd = init_dedup_device_replay(C, OBS, frame_capacity=128)
        ds = init_device_replay(C, OBS)
        dd = ingest_dedup(dd, dedup, prios)
        add = jax.jit(device_replay_add, donate_argnums=(0,))
        for t, p in zip(dense, prios):
            ds = add(ds, jax.device_put(t), jnp.asarray(p))

        state_a, step_a = build_learner()
        state_b, step_b = build_learner()
        K, B = 5, 8
        fused_ds = build_fused_learn_step(
            step_a, B, steps_per_call=K, target_sync_freq=10,
            include_ingest=False, sample_ahead=sample_ahead,
        )
        fused_dd = build_dedup_fused_learn_step(
            step_b, B, steps_per_call=K, target_sync_freq=10,
            sample_ahead=sample_ahead,
        )
        rng = jax.random.PRNGKey(42)
        for i in range(3):
            rng, sub = jax.random.split(rng)
            state_a, ds, m_a = fused_ds(state_a, ds, 0.4, sub)
            state_b, dd, m_b = fused_dd(state_b, dd, 0.4, sub)
            np.testing.assert_array_equal(
                np.asarray(m_a.priorities), np.asarray(m_b.priorities),
                err_msg=f"call {i} priorities",
            )
            jax.tree_util.tree_map(
                lambda x, y: np.testing.assert_allclose(
                    np.asarray(x), np.asarray(y), rtol=0, atol=0
                ),
                state_a.params, state_b.params,
            )
        np.testing.assert_array_equal(
            np.asarray(ds.mass), np.asarray(dd.mass)
        )
        assert int(state_a.step) == int(state_b.step) == 15
