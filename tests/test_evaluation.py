"""Evaluation harness (evaluation.py): greedy eval fleet, human-normalized
scoring, runtime wiring (--eval-every) — the scoring path for the north-star
"Atari median human-normalized score" metric that the reference lacks
entirely (its only metric is the exploring actor's episode-reward print,
reference actor.py:177)."""

import numpy as np
import pytest

from ape_x_dqn_tpu.config import ApexConfig
from ape_x_dqn_tpu.envs.core import StepResult
from ape_x_dqn_tpu.evaluation import (
    GreedyEvaluator,
    canonical_game,
    human_normalized,
    median_human_normalized,
)


class TestScoreTable:
    def test_canonical_game_strips_suffixes(self):
        assert canonical_game("PongNoFrameskip-v4") == "Pong"
        assert canonical_game("Pong-v4") == "Pong"
        assert canonical_game("PongDeterministic-v4") == "Pong"
        assert canonical_game("pong") == "Pong"
        assert canonical_game("chain:6") == "chain"

    def test_canonical_game_strips_namespace_prefix(self):
        # gymnasium v5 spelling (round-4 advisor: eval/hns silently became
        # None for namespaced ids).
        assert canonical_game("ALE/Pong-v5") == "Pong"
        assert canonical_game("ALE/MsPacman-v5") == "MsPacman"
        assert canonical_game("gym:ALE/Pong-v5") == "Pong"
        assert canonical_game("gym:CartPole-v1") == "CartPole"

    def test_human_normalized_anchors(self):
        # By construction: random play = 0, human = 1.
        assert human_normalized("PongNoFrameskip-v4", -20.7) == pytest.approx(0.0)
        assert human_normalized("PongNoFrameskip-v4", 14.6) == pytest.approx(1.0)
        # Superhuman > 1 (Ape-X's regime on most games).
        assert human_normalized("BreakoutNoFrameskip-v4", 300.0) > 1.0

    def test_non_atari_returns_none(self):
        assert human_normalized("chain:6", 1.0) is None
        assert human_normalized("catch", 0.5) is None

    def test_median_over_suite(self):
        scores = {
            "PongNoFrameskip-v4": 14.6,       # hns 1.0
            "BreakoutNoFrameskip-v4": 1.7,    # hns 0.0
            "SeaquestNoFrameskip-v4": 21061.55,  # hns ~0.5
            "chain:6": 1.0,                   # excluded (no table entry)
        }
        assert median_human_normalized(scores) == pytest.approx(0.5, abs=1e-3)
        assert median_human_normalized({"chain:6": 1.0}) is None

    def test_table_covers_sweep_suite(self):
        import sys
        from pathlib import Path

        sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))
        try:
            from sweep import ATARI_57
        finally:
            sys.path.pop(0)
        from ape_x_dqn_tpu.evaluation import ATARI_HUMAN_RANDOM

        missing = [g for g in ATARI_57 if g not in ATARI_HUMAN_RANDOM]
        assert not missing, f"no human/random entry for: {missing}"


class FixedEpisodeEnv:
    """Every episode: 4 steps of reward 2.5 then terminate — score 10.0
    regardless of policy.  Isolates the evaluator's episode accounting."""

    observation_shape = (3,)
    num_actions = 2

    def __init__(self):
        self._t = 0

    def reset(self, seed=None):
        self._t = 0
        return np.zeros(3, np.uint8)

    def step(self, action):
        self._t += 1
        return StepResult(np.zeros(3, np.uint8), 2.5, self._t >= 4, False)


class TestGreedyEvaluator:
    def test_counts_episodes_and_scores(self):
        import jax

        from ape_x_dqn_tpu.models.dueling import DuelingMLP

        net = DuelingMLP(num_actions=2, hidden_sizes=(8,))
        params = net.init(jax.random.PRNGKey(0), np.zeros((1, 3), np.uint8))
        ev = GreedyEvaluator(
            [FixedEpisodeEnv] * 3, net, env_name="fixed", seed=1
        )
        res = ev.evaluate(params, episodes=7)
        assert len(res.episodes) == 7
        assert res.mean_score == pytest.approx(10.0)
        assert res.median_score == pytest.approx(10.0)
        assert res.hns is None  # not an Atari game

    def test_repeated_evals_sample_independent_starts(self):
        """Successive evaluate() calls must NOT replay identical initial
        conditions (round-4 advisor: same reset seed + rng step 0 every call
        gave correlated score estimates over training)."""
        import jax

        from ape_x_dqn_tpu.models.dueling import DuelingMLP

        net = DuelingMLP(num_actions=2, hidden_sizes=(8,))
        params = net.init(jax.random.PRNGKey(0), np.zeros((1, 3), np.uint8))
        ev = GreedyEvaluator(
            [FixedEpisodeEnv] * 2, net, env_name="fixed", seed=1
        )
        seeds = []
        inner_reset = ev.envs.reset
        ev.envs.reset = lambda seed=None: (seeds.append(seed), inner_reset(seed=seed))[1]
        ev.evaluate(params, episodes=2)
        ev.evaluate(params, episodes=2)
        ev.evaluate(params, episodes=2)
        assert len(set(seeds)) == 3, f"reset seeds repeated: {seeds}"

    def test_trained_chain_policy_scores_optimal(self):
        """Greedy eval of a trained chain policy: every episode reaches the
        terminal (+1) — eval/score reports the POLICY's quality, not the
        ε-ladder's exploration returns (which hover near 0 on the chain)."""
        from ape_x_dqn_tpu.runtime import SingleProcessDriver

        cfg = ApexConfig()
        cfg.env.name = "chain:6"
        cfg.network = "mlp"
        cfg.actor.num_actors = 4
        cfg.actor.flush_every = 8
        cfg.actor.gamma = 0.8
        cfg.learner.min_replay_mem_size = 200
        cfg.learner.q_target_sync_freq = 25
        cfg.learner.learning_rate = 3e-3
        cfg.learner.optimizer = "adam"
        cfg.replay.capacity = 5000
        cfg.validate()
        driver = SingleProcessDriver(cfg, learner_steps_per_iter=4)
        driver.run(learner_steps=1500)
        ev = GreedyEvaluator(
            driver.comps.env_fns[:2], driver.network,
            env_name=cfg.env.name, seed=7,
        )
        res = ev.evaluate(driver.state.params, episodes=4)
        assert res.mean_score == pytest.approx(1.0), res
        assert res.hns is None


class TestHNSEndToEnd:
    """VERDICT weak #7: the median-HNS aggregation path exercised END TO
    END — real GreedyEvaluator rollouts over the full DQN wrapper stack on
    the ALE-faithful fake emulator, scores flowing through the human/random
    table into the suite-level median, with an unknown-game fallback."""

    GAMES = {
        # table id -> per-step reward of that fake "game" (clip off, so
        # magnitudes differ and each game lands a distinct raw score).
        "PongNoFrameskip-v4": 3.0,
        "ALE/Breakout-v5": 7.0,
        "SeaquestNoFrameskip-v4": 11.0,
    }

    @staticmethod
    def _env_fn(reward):
        from ape_x_dqn_tpu.envs.atari import wrap_dqn
        from ape_x_dqn_tpu.envs.fake_atari import FakeAtariEnv

        # clip_rewards=False: the raw reward magnitude IS the game's
        # signature, so the three games produce three distinct scores.
        return lambda: wrap_dqn(
            FakeAtariEnv(reward=reward), frame_skip=4, clip_rewards=False
        )

    def test_median_hns_over_fake_atari_suite(self):
        import jax

        from ape_x_dqn_tpu.models.dueling import DuelingMLP

        net = DuelingMLP(num_actions=4, hidden_sizes=(16,))
        params = net.init(
            jax.random.PRNGKey(0), np.zeros((1, 84, 84, 1), np.uint8)
        )
        suite_scores = {}
        per_game_hns = {}
        for name, reward in self.GAMES.items():
            ev = GreedyEvaluator(
                [self._env_fn(reward)] * 2, net, env_name=name, seed=3
            )
            res = ev.evaluate(params, episodes=2)
            assert len(res.episodes) == 2
            assert np.isfinite(res.mean_score)
            # The evaluator itself routed the score through the table.
            assert res.hns == pytest.approx(
                human_normalized(name, res.mean_score)
            )
            suite_scores[name] = res.mean_score
            per_game_hns[name] = res.hns
        # Distinct games produced distinct scores (the suite isn't
        # degenerately measuring one curve three times).
        assert len(set(suite_scores.values())) == 3
        # Unknown-game fallback: a fake game with no table entry is
        # EXCLUDED from the median, not scored as zero.
        ev = GreedyEvaluator(
            [self._env_fn(5.0)] * 2, net, env_name="fake-atari", seed=3
        )
        res_unknown = ev.evaluate(params, episodes=2)
        assert res_unknown.hns is None
        suite_scores["fake-atari"] = res_unknown.mean_score
        med = median_human_normalized(suite_scores)
        assert med == pytest.approx(
            float(np.median(sorted(per_game_hns.values())))
        )
        # All-unknown suite: no headline rather than a fabricated one.
        assert median_human_normalized(
            {"fake-atari": 1.0, "also-not-a-game": 2.0}
        ) is None


class TestRuntimeWiring:
    def test_async_pipeline_emits_eval_metrics(self):
        import io
        import json

        from ape_x_dqn_tpu.runtime import AsyncPipeline
        from ape_x_dqn_tpu.utils.metrics import MetricLogger

        cfg = ApexConfig()
        cfg.env.name = "chain:6"
        cfg.network = "mlp"
        cfg.actor.num_actors = 4
        cfg.actor.flush_every = 8
        cfg.learner.min_replay_mem_size = 256
        cfg.learner.optimizer = "adam"
        cfg.learner.learning_rate = 1e-3
        cfg.replay.capacity = 10_000
        cfg.validate()
        buf = io.StringIO()
        pipe = AsyncPipeline(
            cfg, logger=MetricLogger(stream=buf), log_every=50,
            eval_every=60, eval_episodes=2,
        )
        pipe.run(learner_steps=130, warmup_timeout=120.0)
        assert len(pipe.eval_scores) >= 2  # evals at ~60 and ~120
        lines = [json.loads(l) for l in buf.getvalue().splitlines()]
        assert any("eval/score" in rec for rec in lines)
