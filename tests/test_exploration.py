"""ε-ladder values and ε-greedy behavior."""

import jax
import jax.numpy as jnp
import numpy as np

from ape_x_dqn_tpu.ops.exploration import epsilon_greedy, epsilon_ladder


def test_ladder_matches_apex_formula():
    # eps_i = eps^(1 + alpha*i/(N-1)), eps=0.4, alpha=7 (reference actor.py:114)
    eps, alpha, N = 0.4, 7.0, 5
    got = np.asarray(epsilon_ladder(eps, alpha, N))
    expected = [eps ** (1 + alpha * i / (N - 1)) for i in range(N)]
    np.testing.assert_allclose(got, expected, rtol=1e-5)
    assert got[0] == np.float32(0.4)
    assert np.all(np.diff(got) < 0)  # monotonically more greedy


def test_ladder_single_actor():
    np.testing.assert_allclose(np.asarray(epsilon_ladder(0.4, 7.0, 1)), [0.4])


def test_epsilon_zero_is_greedy():
    q = jnp.asarray([[0.0, 1.0], [5.0, -1.0]])
    a = epsilon_greedy(jax.random.PRNGKey(0), q, jnp.zeros(2))
    np.testing.assert_array_equal(np.asarray(a), [1, 0])


def test_epsilon_one_is_uniform():
    q = jnp.tile(jnp.asarray([[0.0, 10.0, 0.0, 0.0]]), (4000, 1))
    a = epsilon_greedy(jax.random.PRNGKey(1), q, jnp.ones(4000))
    counts = np.bincount(np.asarray(a), minlength=4)
    assert (counts > 800).all()  # roughly uniform over 4 actions


def test_per_actor_epsilon_broadcast():
    # actor 0 epsilon=1 (random), actor 1 epsilon=0 (greedy)
    q = jnp.tile(jnp.asarray([[0.0, 10.0]]), (2000, 1))
    eps = jnp.asarray([1.0, 0.0] * 1000)
    a = np.asarray(epsilon_greedy(jax.random.PRNGKey(2), q, eps))
    greedy_slots = a[1::2]
    np.testing.assert_array_equal(greedy_slots, np.ones_like(greedy_slots))
    assert (a[0::2] == 0).sum() > 300  # random slots explore action 0 sometimes
