"""Central inference (SEED-style paramless actors) — ISSUE 12.

Covers: the batched F_IREQ/F_IREP codec, the v2 serve hello's run-token
discipline, the live server's adversarial decode matrix on the
obs→inference path (torn/bitflipped/oversize request AND reply frames
counted, never decoded), whole-request retry applied exactly once per
lost reply, the ε-ladder slice identity pin (worker-side ε on the
returned argmax, same global partition as local mode), the typed
serving-outage degradation path (block-with-stall vs local fallback),
the fleet's selector seam, the obs `inference` schema contract, the
replay-service `service_codec=auto` reply gate, and seeded
central-vs-local convergence parity on fake-atari."""

import io
import os
import socket
import struct
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from ape_x_dqn_tpu.runtime.net import (
    E_BAD_REQUEST,
    E_OVERLOADED,
    F_IREP,
    F_IREQ,
    F_SERR,
    FRAME,
    CODEC_OFF,
    CODEC_ZLIB,
    FrameParser,
    decode_error,
    decode_inference_reply,
    decode_inference_request,
    encode_inference_reply,
    encode_inference_request,
    frame_bytes,
    parse_serve_hello_ext,
    serve_hello_bytes,
    serve_hello_ext_bytes,
)
from ape_x_dqn_tpu.serving.batcher import ServedAction, ServerOverloaded
from ape_x_dqn_tpu.serving.central import (
    CentralInferenceClient,
    CentralSelector,
    InferenceUnavailable,
    aggregate_inference_stats,
    split_groups,
)
from ape_x_dqn_tpu.serving.net_server import ServingNetServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class StubPolicy:
    """PolicyServer stand-in: greedy action = obs row sum mod A."""

    def __init__(self, num_actions: int = 4, version: int = 7):
        self.num_actions = num_actions
        self.param_version = version
        self.served = 0
        self.fail_with = None

    def q_row(self, obs) -> np.ndarray:
        a = int(np.asarray(obs, np.uint64).sum()) % self.num_actions
        q = np.zeros(self.num_actions, np.float32)
        q[a] = 1.0
        return q

    def submit(self, obs) -> Future:
        if self.fail_with is not None:
            raise self.fail_with
        f = Future()
        self.served += 1
        q = self.q_row(obs)
        f.set_result(ServedAction(
            int(q.argmax()), q, self.param_version, 0.0,
        ))
        return f


@pytest.fixture
def net_server():
    srv = ServingNetServer(StubPolicy(), run_token=4242).start()
    yield srv
    srv.close()


def _client(srv, **kw):
    kw.setdefault("token", 4242)
    kw.setdefault("seed", 1)
    return CentralInferenceClient("127.0.0.1", srv.port, **kw)


def _obs(n=6, shape=(8, 8, 1), seed=0):
    return np.random.default_rng(seed).integers(
        0, 255, (n, *shape), dtype=np.uint8
    )


def _wait(cond, timeout=5.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(f"timeout waiting for {msg}")


class TestInferenceCodec:
    def test_request_roundtrip_bit_exact(self):
        obs = _obs(5, (4, 12, 12))
        obs[3] = obs[1]          # identical rows: the dedup window's prey
        for codec in (CODEC_OFF, CODEC_ZLIB):
            payload, st = encode_inference_request(9, obs, codec=codec)
            rid, rows = decode_inference_request(payload)
            assert rid == 9 and len(rows) == 5
            for i in range(5):
                np.testing.assert_array_equal(rows[i], obs[i])
        # The duplicate row deduped: 4 plane refs, full row's bytes saved.
        assert st["dedup_hits"] == 4
        assert st["dedup_bytes"] == obs[1].nbytes

    def test_reply_roundtrip(self):
        acts = np.array([2, 0, 1], np.int32)
        q = np.arange(9, dtype=np.float32).reshape(3, 3)
        rid, back_a, ver, back_q = decode_inference_reply(
            encode_inference_reply(5, acts, 33, q)
        )
        assert (rid, ver) == (5, 33)
        np.testing.assert_array_equal(back_a, acts)
        np.testing.assert_array_equal(back_q, q)

    def test_reply_geometry_mismatch_raises(self):
        body = bytearray(encode_inference_reply(
            1, np.zeros(2, np.int32), 0, np.zeros((2, 3), np.float32)
        ))
        with pytest.raises(ValueError):
            decode_inference_reply(bytes(body[:-1]))

    def test_row_count_head_mismatch_raises(self):
        payload = bytearray(encode_inference_request(1, _obs(3))[0])
        # Head says 4 rows, body carries 3.
        struct.pack_into("<I", payload, 8, 4)
        with pytest.raises(ValueError, match="rows"):
            decode_inference_request(bytes(payload))

    def test_compressed_on_off_negotiation_raises(self):
        payload, st = encode_inference_request(
            1, np.zeros((4, 64, 64, 1), np.uint8), codec=CODEC_ZLIB
        )
        assert st["compressed"]
        with pytest.raises(ValueError, match="codec"):
            decode_inference_request(payload, allow_zlib=False)


class TestHelloToken:
    def test_ext_hello_roundtrip(self):
        h = serve_hello_ext_bytes(3, 2, 99, CODEC_ZLIB)
        ext = parse_serve_hello_ext(h[8:])
        assert ext == {"wid": 3, "attempt": 2, "token": 99,
                       "codec": CODEC_ZLIB, "flags": 0}
        # The flags byte lives in what was pad: a flags-0 hello is
        # byte-identical to the pre-flags wire, and a trace-flagged one
        # round-trips the bit.
        from ape_x_dqn_tpu.runtime.net import HELLO_FLAG_TRACE

        traced = serve_hello_ext_bytes(3, 2, 99, CODEC_ZLIB,
                                       flags=HELLO_FLAG_TRACE)
        assert parse_serve_hello_ext(traced[8:])["flags"] == HELLO_FLAG_TRACE
        assert traced != h and len(traced) == len(h)

    def test_wrong_token_rejected_before_framing(self, net_server):
        s = socket.create_connection(("127.0.0.1", net_server.port), 5.0)
        s.sendall(serve_hello_ext_bytes(0, 0, 1, CODEC_OFF))
        _wait(lambda: net_server.token_rejects == 1, msg="token reject")
        assert net_server.stats()["requests"] == 0
        s.close()

    def test_anonymous_v1_hello_still_accepted(self, net_server):
        # The single-request front door stays public even with a token
        # set: v1 hellos carry no token and are admitted.
        from ape_x_dqn_tpu.runtime.net import F_SREQ, encode_request

        s = socket.create_connection(("127.0.0.1", net_server.port), 5.0)
        s.sendall(serve_hello_bytes())
        s.sendall(frame_bytes(
            F_SREQ, 1, [encode_request(1, np.zeros(8, np.uint8))]
        ))
        _wait(lambda: net_server.replies == 1, msg="v1 reply")
        s.close()

    def test_good_token_lands_per_source_stats(self, net_server):
        cl = _client(net_server, wid=11)
        try:
            cl.select(_obs(4), timeout_s=10)
        finally:
            cl.close()
        src = net_server.stats()["sources"]
        assert src["11"]["rows"] == 4
        assert src["11"]["replies"] >= 1


class TestServerInference:
    def test_batched_select_matches_stub(self, net_server):
        obs = _obs(7)
        cl = _client(net_server, inflight=3)
        try:
            actions, q, version = cl.select(obs, timeout_s=10)
        finally:
            cl.close()
        stub = StubPolicy()
        want = np.array([stub.q_row(o).argmax() for o in obs], np.int32)
        np.testing.assert_array_equal(actions, want)
        assert version == 7
        assert q.shape == (7, 4)
        st = net_server.stats()
        assert st["inference_requests"] == 3       # inflight groups
        assert st["inference_rows"] == 7
        assert st["torn_frames"] == 0

    def test_zlib_negotiated_end_to_end(self, net_server):
        cl = _client(net_server, codec="zlib",
                     inflight=1)
        try:
            obs = np.zeros((6, 32, 32, 1), np.uint8)   # compresses well
            actions, _q, _v = cl.select(obs, timeout_s=10)
        finally:
            cl.close()
        assert cl.compressed_frames >= 1
        assert cl.wire_bytes_out < obs.nbytes      # the codec won
        assert net_server.stats()["torn_frames"] == 0

    def test_shed_is_typed_and_retried(self, net_server):
        stub = net_server._server
        stub.fail_with = ServerOverloaded("full")
        cl = _client(net_server)

        def lift():
            time.sleep(0.3)
            stub.fail_with = None

        t = threading.Thread(target=lift)
        t.start()
        try:
            actions, _q, _v = cl.select(_obs(4), timeout_s=15)
            assert actions.shape == (4,)
            assert cl.shed_seen >= 1       # refusals were typed, counted
            assert cl.torn_replies == 0    # ...and never torn
        finally:
            t.join()
            cl.close()

    def test_bad_body_typed_not_torn(self, net_server):
        s = socket.create_connection(("127.0.0.1", net_server.port), 5.0)
        s.sendall(serve_hello_ext_bytes(0, 0, 4242, CODEC_OFF))
        # Well-framed F_IREQ whose body is garbage: crc passes, decode
        # must reply typed E_BAD_REQUEST — not count torn.
        s.sendall(frame_bytes(F_IREQ, 1, [b"\x99" * 64]))
        parser = FrameParser()
        deadline = time.monotonic() + 5.0
        got = None
        while got is None and time.monotonic() < deadline:
            parser.feed(s.recv(4096))
            got = parser.next()
        kind, payload = got
        assert kind == F_SERR
        assert decode_error(payload)[1] == E_BAD_REQUEST
        assert net_server.torn_frames == 0
        s.close()

    def test_torn_request_frames_never_decoded(self, net_server):
        """Truncation / crc bitflip / oversize prefix on the F_IREQ
        plane: counted torn, nothing reaches the batcher."""
        stub = net_server._server
        good = frame_bytes(
            F_IREQ, 1, [encode_inference_request(1, _obs(4))[0]]
        )
        cases = []
        cases.append(good[: FRAME.size + 10])             # truncated body
        flipped = bytearray(good)
        flipped[FRAME.size + 4] ^= 0x40                   # payload bitflip
        cases.append(bytes(flipped))
        huge = bytearray(good)
        struct.pack_into("<I", huge, 0, 1 << 29)          # absurd length
        cases.append(bytes(huge))
        before = stub.served
        for i, wire in enumerate(cases):
            torn0 = net_server.torn_frames
            s = socket.create_connection(
                ("127.0.0.1", net_server.port), 5.0
            )
            s.sendall(serve_hello_ext_bytes(0, 0, 4242, CODEC_OFF))
            s.sendall(wire)
            s.shutdown(socket.SHUT_WR)
            _wait(lambda: net_server.torn_frames > torn0,
                  msg=f"torn case {i}")
            s.close()
        assert stub.served == before        # nothing decoded, ever


class _FlippingProxy:
    """TCP proxy that XORs one byte of the Nth server→client payload
    byte window — the bitflipped-REPLY-stream shape."""

    def __init__(self, dst_port: int, flip_at: int = 60):
        self._dst = dst_port
        self._flip_at = flip_at
        self._flipped = False
        self._lsock = socket.socket()
        self._lsock.bind(("127.0.0.1", 0))
        self._lsock.listen(8)
        self.port = self._lsock.getsockname()[1]
        self._stop = False
        self._threads = []
        t = threading.Thread(target=self._accept, daemon=True)
        t.start()
        self._threads.append(t)

    def _accept(self):
        while not self._stop:
            try:
                c, _ = self._lsock.accept()
            except OSError:
                return
            u = socket.create_connection(("127.0.0.1", self._dst), 5.0)
            for src, dst, flip in ((c, u, False), (u, c, True)):
                t = threading.Thread(
                    target=self._pump, args=(src, dst, flip), daemon=True
                )
                t.start()
                self._threads.append(t)

    def _pump(self, src, dst, flip):
        seen = 0
        while not self._stop:
            try:
                data = src.recv(4096)
            except OSError:
                break
            if not data:
                break
            if flip and not self._flipped and seen + len(data) > \
                    self._flip_at:
                b = bytearray(data)
                b[self._flip_at - seen] ^= 0x10
                data = bytes(b)
                self._flipped = True
            seen += len(data)
            try:
                dst.sendall(data)
            except OSError:
                break
        for s in (src, dst):
            try:
                s.close()
            except OSError:
                pass

    def close(self):
        self._stop = True
        try:
            self._lsock.close()
        except OSError:
            pass


class TestClientAdversarial:
    def test_bitflipped_reply_dropped_and_retried(self, net_server):
        proxy = _FlippingProxy(net_server.port, flip_at=40)
        cl = CentralInferenceClient(
            "127.0.0.1", proxy.port, token=4242, seed=2, inflight=1,
        )
        try:
            obs = _obs(4)
            actions, _q, _v = cl.select(obs, timeout_s=20)
            stub = StubPolicy()
            want = np.array(
                [stub.q_row(o).argmax() for o in obs], np.int32
            )
            np.testing.assert_array_equal(actions, want)
            # The flipped stream was detected torn client-side, never
            # decoded, and the request retried whole.
            assert cl.torn_replies >= 1
            assert cl.retries >= 1
        finally:
            cl.close()
            proxy.close()

    def test_lost_reply_retried_exactly_once(self):
        """A server that swallows the FIRST request: the client's io
        deadline expires, it reconnects and resends the request WHOLE —
        exactly one retry round for one lost reply."""
        stub = StubPolicy()
        srv = ServingNetServer(stub, run_token=4242).start()
        orig = srv._handle_inference
        dropped = {"n": 0}

        def dropping(conn, payload):
            if dropped["n"] == 0:
                dropped["n"] += 1
                return            # swallow: no reply, no error
            orig(conn, payload)

        srv._handle_inference = dropping
        cl = CentralInferenceClient(
            "127.0.0.1", srv.port, token=4242, seed=3, inflight=1,
            io_timeout_s=0.5,
        )
        try:
            cl.select(_obs(3), timeout_s=20)
            assert dropped["n"] == 1
            assert cl.retries == 1
        finally:
            cl.close()
            srv.close()

    def test_outage_is_typed(self):
        # Nothing listening: the deadline expires into the TYPED signal.
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        cl = CentralInferenceClient("127.0.0.1", port, seed=4)
        try:
            with pytest.raises(InferenceUnavailable):
                cl.select(_obs(2), timeout_s=1.0)
            assert cl.stall_s > 0
        finally:
            cl.close()


class TestSelector:
    def test_epsilon_ladder_slice_identity(self):
        """The partition pin: worker wid's central-mode ε slice IS the
        global ladder slice local mode would use — actor identity is
        placement- and inference-mode-independent."""
        from ape_x_dqn_tpu.ops.exploration import epsilon_ladder
        from ape_x_dqn_tpu.runtime.process_actors import worker_slice

        N, W = 16, 4
        ladder = np.asarray(epsilon_ladder(0.4, 7.0, N))
        for wid in range(W):
            lo, hi = worker_slice(wid, N, W)
            sel = CentralSelector(
                CentralInferenceClient("127.0.0.1", 1, seed=0),
                ladder[lo:hi], 4,
            )
            np.testing.assert_allclose(sel.epsilons, ladder[lo:hi])
            sel.close()

    def test_epsilon_zero_is_server_greedy(self, net_server):
        obs = _obs(5)
        cl = _client(net_server)
        sel = CentralSelector(cl, np.zeros(5), 4, seed=9)
        try:
            actions, q, _v = sel.select(obs, 0)
        finally:
            sel.close()
        stub = StubPolicy()
        want = np.array([stub.q_row(o).argmax() for o in obs], np.int32)
        np.testing.assert_array_equal(actions, want)
        np.testing.assert_array_equal(
            actions, np.asarray(q).argmax(axis=1)
        )

    def test_epsilon_one_is_seeded_uniform(self, net_server):
        obs = _obs(64)
        cl = _client(net_server)
        sel = CentralSelector(cl, np.ones(64), 4, seed=9)
        cl2 = _client(net_server)
        sel2 = CentralSelector(cl2, np.ones(64), 4, seed=9)
        try:
            a1, _, _ = sel.select(obs, 0)
            a2, _, _ = sel2.select(obs, 0)
        finally:
            sel.close()
            sel2.close()
        np.testing.assert_array_equal(a1, a2)   # seeded: reproducible
        assert len(np.unique(a1)) == 4          # ...and actually random

    def test_outage_uses_local_fallback(self):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        calls = []

        def fallback(obs, step):
            calls.append(step)
            return (np.zeros(obs.shape[0], np.int32),
                    np.zeros((obs.shape[0], 4), np.float32), 3)

        cl = CentralInferenceClient("127.0.0.1", port, seed=5)
        sel = CentralSelector(cl, np.zeros(2), 4, timeout_s=0.5,
                              fallback=fallback)
        try:
            actions, _q, version = sel.select(_obs(2), 17)
        finally:
            sel.close()
        assert calls == [17]
        assert version == 3
        assert sel.outages == 1
        assert cl.fallback_steps == 1

    def test_outage_without_fallback_blocks_until_stop(self):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        stop = threading.Event()
        cl = CentralInferenceClient("127.0.0.1", port, seed=6)
        sel = CentralSelector(cl, np.zeros(2), 4, timeout_s=0.3,
                              should_stop=stop.is_set)
        threading.Timer(1.0, stop.set).start()
        t0 = time.monotonic()
        try:
            with pytest.raises(InferenceUnavailable):
                sel.select(_obs(2), 0)
        finally:
            sel.close()
        # It blocked past the per-attempt deadline (outages counted) and
        # only gave up when stopped.
        assert time.monotonic() - t0 >= 0.9
        assert sel.outages >= 1
        assert cl.stall_s > 0

    def test_split_groups_balanced(self):
        assert split_groups(7, 3) == [(0, 2), (2, 4), (4, 7)]
        assert split_groups(2, 8) == [(0, 1), (1, 2)]


class TestFleetSeam:
    def test_collect_with_selector_is_paramless(self, net_server):
        """ActorFleet.collect(selector=...) never touches params and
        adopts the reply version; chunks/priorities flow as local."""
        from ape_x_dqn_tpu.actors import ActorFleet
        from ape_x_dqn_tpu.models.dueling import build_network

        net = build_network("mlp", 2)
        env_fns = [
            (lambda i=i: __import__(
                "ape_x_dqn_tpu.envs", fromlist=["make_env"]
            ).make_env("chain:6", seed=100 + i))
            for i in range(4)
        ]
        fleet = ActorFleet(env_fns, net, n_step=3, flush_every=8, seed=0)
        cl = _client(net_server)
        sel = CentralSelector(cl, np.asarray(fleet._epsilons), 2, seed=1)
        try:
            chunks, _stats = fleet.collect(24, selector=sel)
        finally:
            sel.close()
        assert fleet.params is None            # truly paramless
        assert fleet.param_version == 7        # adopted from replies
        assert chunks and all(
            np.isfinite(c.priorities).all() for c in chunks
        )

    def test_collect_without_selector_still_requires_params(self):
        from ape_x_dqn_tpu.actors import ActorFleet
        from ape_x_dqn_tpu.envs import make_env
        from ape_x_dqn_tpu.models.dueling import build_network

        fleet = ActorFleet(
            [lambda: make_env("chain:6", seed=0)],
            build_network("mlp", 2), seed=0,
        )
        with pytest.raises(RuntimeError, match="no params"):
            fleet.collect(4)


def _doc_keys(section_header):
    # Shared parser (apexlint satellite): one implementation in
    # ape_x_dqn_tpu/analysis/metrics_doc.py serves every schema pin.
    from ape_x_dqn_tpu.analysis.metrics_doc import doc_section_keys

    return doc_section_keys(
        section_header, os.path.join(REPO, "docs", "METRICS.md"))


@pytest.fixture(scope="module")
def central_thread_run():
    """One small central-mode thread run (chain MDP, auto in-process
    serving tier) shared by the schema + freshness tests."""
    from ape_x_dqn_tpu.config import ApexConfig
    from ape_x_dqn_tpu.runtime.async_pipeline import AsyncPipeline
    from ape_x_dqn_tpu.utils.metrics import MetricLogger

    cfg = ApexConfig()
    cfg.network = "mlp"
    cfg.env.name = "chain:6"
    cfg.actor.num_actors = 4
    cfg.actor.T = 100_000
    cfg.actor.flush_every = 8
    cfg.actor.sync_every = 16
    cfg.actor.inference = "central"
    cfg.actor.inference_inflight = 2
    cfg.actor.inference_codec = "zlib"
    cfg.serving.max_batch = 8
    cfg.serving.max_wait_ms = 2.0
    cfg.learner.min_replay_mem_size = 256
    cfg.learner.publish_every = 5
    cfg.learner.total_steps = 80
    cfg.learner.optimizer = "adam"
    cfg.replay.capacity = 4096
    cfg.validate()
    buf = io.StringIO()
    pipe = AsyncPipeline(cfg, logger=MetricLogger(stream=buf), log_every=40)
    final = pipe.run(learner_steps=80, warmup_timeout=180.0)
    return {"final_record": final, "pipe": pipe}


class TestObsSchema:
    def test_inference_section_matches_doc(self, central_thread_run):
        doc = _doc_keys("## Inference schema")
        assert doc, "Inference schema doc section missing"
        rec = central_thread_run["final_record"]
        assert "inference" in rec, "inference section absent from emit"
        assert set(doc) == set(rec["inference"]), (
            set(doc) ^ set(rec["inference"])
        )

    def test_serving_net_doc_covers_new_keys(self):
        doc = _doc_keys("## Serving net schema")
        for k in ("token_rejects", "inference_requests",
                  "inference_rows", "inference_replies", "sources"):
            assert k in doc, k

    def test_central_run_is_fresh_and_clean(self, central_thread_run):
        inf = central_thread_run["final_record"]["inference"]
        assert inf["mode"] == "central"
        assert inf["replies"] > 0
        assert inf["torn_replies"] == 0
        assert inf["param_version"] >= 1
        # Freshness: replies track the store within a couple publishes
        # (the reload poll cadence bounds the lag).
        assert inf["version_lag"] is not None and inf["version_lag"] <= 5
        assert inf["rtt"]["count"] > 0
        # And the in-process batcher really batched across the fleet.
        assert inf["batch_occupancy_mean"] is not None

    def test_varz_provider_registered(self, central_thread_run):
        snap = central_thread_run["pipe"].obs_registry.snapshot()
        assert "inference" in snap
        assert snap["inference"]["mode"] == "central"


class TestAggregation:
    def test_aggregate_merges_counters_and_rtt(self):
        from ape_x_dqn_tpu.utils.metrics import LatencyHistogram

        h1, h2 = LatencyHistogram(), LatencyHistogram()
        h1.record(0.01)
        h2.record(0.1)
        dicts = []
        for h, reqs, v in ((h1, 3, 5), (h2, 4, 9)):
            with h._lock:
                state = {"counts": list(h._counts), "count": h._count,
                         "sum": h._sum, "max": h._max}
            dicts.append({
                "requests": reqs, "rows": reqs, "replies": reqs,
                "retries": 0, "reconnects": 0, "shed_seen": 0,
                "torn_replies": 0, "errors": 0, "fallback_steps": 0,
                "selects": reqs, "outages": 0, "stall_ms": 1.5,
                "param_version": v, "wire_bytes_out": 10,
                "logical_bytes_out": 20, "rtt_state": state,
            })
        out = aggregate_inference_stats(dicts)
        assert out["requests"] == 7
        assert out["param_version"] == 5      # freshness floor
        assert out["stall_ms"] == 3.0
        assert out["rtt"]["count"] == 2
        assert out["wire_over_logical"] == 0.5


class TestReplaySvcAutoCodec:
    def test_auto_gates_on_backpressure(self):
        """service_codec=auto: raw replies while the reply path is
        unblocked; zlib after observed backpressure; raw again after the
        idle decay."""
        from ape_x_dqn_tpu.replay.buffer import PrioritizedReplay
        from ape_x_dqn_tpu.replay.service import ReplayShardServer

        rep = PrioritizedReplay(64, (4, 4, 1))
        srv = ReplayShardServer(rep, 0, codec="auto")
        try:
            assert srv._reply_codec() == CODEC_OFF        # unloaded: raw
            srv.reply_full_waits += 1                     # blocked send
            assert srv._reply_codec() == CODEC_ZLIB       # wire-bound
            for _ in range(400):                          # idle decay
                srv._reply_codec()
            assert srv._reply_codec() == CODEC_OFF
        finally:
            srv.close()

    def test_auto_end_to_end_unloaded_ships_raw(self):
        from ape_x_dqn_tpu.replay.buffer import PrioritizedReplay
        from ape_x_dqn_tpu.replay.service import (
            ReplayShardServer,
            ShardClient,
            ShardedReplayClient,
        )

        rep = PrioritizedReplay(128, (8, 8, 1))
        srv = ReplayShardServer(rep, 0, token=7, codec="auto").start()
        cl = ShardedReplayClient(
            [{"id": 0, "host": "127.0.0.1", "port": srv.port, "base": 0,
              "capacity": 128, "incarnation": srv.incarnation}],
            token=7, codec="auto", request_timeout_s=5.0,
        )
        try:
            rng = np.random.default_rng(0)

            class B:
                pass

            b = B()
            b.obs = rng.integers(0, 255, (32, 8, 8, 1), dtype=np.uint8)
            b.next_obs = np.roll(b.obs, -1, axis=0)
            b.action = np.zeros(32, np.int32)
            b.reward = np.zeros(32, np.float32)
            b.discount = np.ones(32, np.float32)
            cl.add(np.ones(32), b)
            for _ in range(4):
                cl.sample(8, rng=rng)
            sc = ShardClient(0, "127.0.0.1", srv.port, token=7,
                             client_id=99, incarnation=srv.incarnation,
                             codec="auto")
            st = sc.shard_stats(timeout=5.0)
            sc.close()
            assert st["codec_policy"] == "auto"
            assert st["reply_raw"] >= 4       # unloaded loopback: raw
            assert st["reply_zlib"] == 0
        finally:
            cl.close()
            srv.close()


class TestConvergenceParity:
    """Seeded central-vs-local parity on fake-atari: same config, same
    seed, the two inference modes must track the same learning curve
    within tolerance (the rewards are policy-independent by design, so
    the value estimates — mean_q — and the greedy eval score are the
    curve; the structural claims — replies flowed, zero torn, fresh
    versions — make the run central in fact, not just in name)."""

    def _run(self, inference: str):
        from ape_x_dqn_tpu.config import ApexConfig
        from ape_x_dqn_tpu.runtime.async_pipeline import AsyncPipeline
        from ape_x_dqn_tpu.utils.metrics import MetricLogger

        cfg = ApexConfig()
        cfg.network = "mlp"
        cfg.env.name = "fake-atari"
        cfg.actor.num_actors = 2
        cfg.actor.T = 100_000
        cfg.actor.flush_every = 8
        cfg.actor.sync_every = 16
        cfg.actor.inference = inference
        cfg.actor.inference_inflight = 2
        cfg.serving.max_batch = 8
        cfg.serving.max_wait_ms = 2.0
        cfg.learner.min_replay_mem_size = 300
        cfg.learner.publish_every = 10
        cfg.learner.total_steps = 150
        cfg.learner.optimizer = "adam"
        cfg.learner.learning_rate = 1e-3
        cfg.replay.capacity = 4096
        cfg.seed = 11
        cfg.validate()
        buf = io.StringIO()
        pipe = AsyncPipeline(
            cfg, logger=MetricLogger(stream=buf), log_every=75,
            eval_every=150, eval_episodes=2,
        )
        final = pipe.run(learner_steps=150, warmup_timeout=300.0)
        return final, pipe

    def test_central_matches_local_curve(self):
        final_l, pipe_l = self._run("local")
        final_c, pipe_c = self._run("central")
        # Central was really central: selection flowed through the tier.
        inf = final_c["inference"]
        assert inf["replies"] > 0 and inf["torn_replies"] == 0
        assert inf["version_lag"] is not None and inf["version_lag"] <= 5
        # Curve parity: the value estimate both runs converge toward.
        q_l = final_l["learner/mean_q"]
        q_c = final_c["learner/mean_q"]
        assert np.isfinite(q_l) and np.isfinite(q_c)
        assert abs(q_c - q_l) <= 0.5 * max(1.0, abs(q_l)), (q_l, q_c)
        # Eval parity (greedy rollouts on the learned nets).
        s_l = pipe_l.eval_scores[-1]
        s_c = pipe_c.eval_scores[-1]
        assert abs(s_c - s_l) <= 0.25 * max(1.0, abs(s_l)), (s_l, s_c)
