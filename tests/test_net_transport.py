"""TCP experience-transport tests: the framing's adversarial decode
matrix (the socket mirror of tests/test_shm_ring.py's torn-tail matrix),
the param delta codec, channel hijack/reconnect interleaving, the
pool-level salvage discipline on the tcp backend, the per_host transport
budget, and the cross-host clock-skew clamp."""

import socket
import struct
import time

import numpy as np
import pytest

from ape_x_dqn_tpu.runtime.net import (
    _FRAME,
    _HELLO,
    _NET_MAGIC,
    _NET_VERSION,
    F_XP,
    Backoff,
    FrameParser,
    NetTransport,
    NetWriter,
    apply_param_delta,
    build_param_delta,
    build_param_full,
    frame_bytes,
)
from ape_x_dqn_tpu.runtime.shm_ring import XP, decode_chunk, encode_chunk_parts


def _frames(*payloads, start_seq=1):
    return b"".join(
        frame_bytes(F_XP, start_seq + i, [p]) for i, p in enumerate(payloads)
    )


class TestFrameParserAdversarial:
    """Truncation/corruption matrix: every fault is detected (parser
    error or pending tail), nothing invalid is ever yielded — the
    stream-level torn-ring-tail contract."""

    def test_roundtrip_and_order(self):
        p = FrameParser()
        p.feed(_frames(b"one", b"two", b"three"))
        got = [p.next() for _ in range(3)]
        assert [x[1] for x in got] == [b"one", b"two", b"three"]
        assert p.next() is None and p.error is None

    def test_truncation_mid_length_prefix(self):
        p = FrameParser()
        whole = _frames(b"committed", b"torn-after-this")
        p.feed(whole[:len(_frames(b"committed")) + 3])  # 3 B of next header
        assert p.next()[1] == b"committed"
        assert p.next() is None           # incomplete header: nothing out
        assert p.error is None
        assert p.pending() == 3           # the torn tail, detectable

    def test_truncation_mid_payload(self):
        p = FrameParser()
        whole = _frames(b"x" * 1000)
        p.feed(whole[:_FRAME.size + 137])
        assert p.next() is None
        assert p.pending() == _FRAME.size + 137

    def test_crc_bitflip_detected(self):
        buf = bytearray(_frames(b"a" * 600))
        buf[_FRAME.size + 300] ^= 0x40    # flip one payload bit
        p = FrameParser()
        p.feed(bytes(buf))
        assert p.next() is None
        assert p.error == "crc"
        p.feed(_frames(b"late", start_seq=2))
        assert p.next() is None           # dead stream yields nothing more

    def test_seq_skip_detected(self):
        p = FrameParser()
        p.feed(frame_bytes(F_XP, 1, [b"one"]))
        p.feed(frame_bytes(F_XP, 3, [b"skipped-two"]))
        assert p.next()[1] == b"one"
        assert p.next() is None
        assert p.error == "seq"

    def test_absurd_length_prefix_rejected(self):
        p = FrameParser()
        p.feed(_FRAME.pack(1 << 31, 0, 1, F_XP))
        assert p.next() is None
        assert p.error == "length"

    def test_byte_dribble_reassembles(self):
        """One byte at a time — frames only emerge complete and verified."""
        whole = _frames(b"dribbled-payload" * 10)
        p = FrameParser()
        out = []
        for i in range(len(whole)):
            p.feed(whole[i:i + 1])
            got = p.next()
            if got is not None:
                out.append(got[1])
        assert out == [b"dribbled-payload" * 10]


class TestParamDelta:
    def test_delta_roundtrip(self):
        rng = np.random.default_rng(0)
        prev = rng.integers(0, 255, 300_000, dtype=np.uint8).tobytes()
        new = bytearray(prev)
        new[1000:1032] = b"\x7f" * 32      # one dirty page
        new = bytes(new)
        d = build_param_delta(7, 6, prev, new)
        assert d is not None and len(d) < len(new) // 4
        version, base, blob = apply_param_delta(prev, d)
        assert (version, base) == (7, 6)
        assert blob == new

    def test_delta_falls_back_to_full_when_everything_moved(self):
        rng = np.random.default_rng(1)
        prev = rng.integers(0, 255, 100_000, dtype=np.uint8).tobytes()
        new = rng.integers(0, 255, 100_000, dtype=np.uint8).tobytes()
        assert build_param_delta(2, 1, prev, new) is None
        assert build_param_delta(2, 1, prev, prev + b"x") is None  # size

    def test_delta_crc_mismatch_raises(self):
        prev = bytes(200_000)
        new = bytearray(prev)
        new[5] = 1
        d = bytearray(build_param_delta(3, 2, prev, bytes(new)))
        d[-1] ^= 0x01                      # corrupt a patched page byte
        with pytest.raises(ValueError, match="crc"):
            apply_param_delta(prev, bytes(d))
        with pytest.raises(ValueError):    # wrong baseline blob
            apply_param_delta(bytes(199_999), bytes(d))

    def test_full_frame_layout(self):
        payload = build_param_full(9, b"blob-bytes")
        (v,) = struct.unpack_from("<q", payload, 0)
        assert v == 9 and payload[8:] == b"blob-bytes"


def _hello(tr, wid=0, attempt=0, token=None, version=_NET_VERSION):
    return _HELLO.pack(_NET_MAGIC, version, wid, attempt,
                       tr.token if token is None else token)


def _connect_raw(tr, **kw):
    s = socket.create_connection(("127.0.0.1", tr.port), timeout=5)
    s.sendall(_hello(tr, **kw))
    return s


def _pump_until(tr, cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        tr.pump()
        if cond():
            return
        time.sleep(0.01)
    raise TimeoutError("condition not reached")


class TestNetTransportChannel:
    def test_handshake_routes_and_reads(self):
        tr = NetTransport()
        try:
            ch = tr.make_channel(0, 0)
            s = _connect_raw(tr)
            _pump_until(tr, lambda: ch.connected)
            s.sendall(_frames(b"r1", b"r2"))
            got = []
            deadline = time.monotonic() + 5
            while len(got) < 2 and time.monotonic() < deadline:
                rec = ch.read_next()
                if rec is not None:
                    got.append(rec)
            assert got == [b"r1", b"r2"]
            assert ch.committed == 2 and not ch.torn_tail()
            s.close()
        finally:
            tr.close()

    def test_bad_token_and_stale_attempt_rejected(self):
        tr = NetTransport()
        try:
            ch = tr.make_channel(0, 1)
            s1 = _connect_raw(tr, token=12345)       # wrong run
            s2 = _connect_raw(tr, attempt=0)         # stale incarnation
            s3 = _connect_raw(tr, wid=9)             # unknown worker
            _pump_until(tr, lambda: tr.rejects >= 3)
            assert not ch.connected
            for s in (s1, s2, s3):
                s.close()
        finally:
            tr.close()

    def test_disconnect_mid_payload_is_torn_never_delivered(self):
        tr = NetTransport()
        try:
            ch = tr.make_channel(0, 0)
            s = _connect_raw(tr)
            _pump_until(tr, lambda: ch.connected)
            s.sendall(_frames(b"whole-record"))
            partial = frame_bytes(F_XP, 2, [b"y" * 4096])[:200]
            s.sendall(partial)
            time.sleep(0.2)
            s.close()
            # Salvage sweep: the committed record arrives, the torn tail
            # never does, and the tear is counted.
            deadline = time.monotonic() + 5
            got = []
            while time.monotonic() < deadline:
                rec = ch.read_next()
                if rec is not None:
                    got.append(rec)
                if not ch.connected and ch.read_next() is None:
                    break
                time.sleep(0.01)
            assert got == [b"whole-record"]
            assert ch.torn_tail() and ch.torn_live >= 1
        finally:
            tr.close()

    def test_interleaved_reconnect_fresh_seq_stream(self):
        """Connection A delivers, dies mid-frame; connection B (same
        worker, fresh hello) adopts with a FRESH seq stream — its frames
        deliver, A's torn tail is counted, nothing interleaves."""
        tr = NetTransport()
        try:
            ch = tr.make_channel(3, 2)
            a = _connect_raw(tr, wid=3, attempt=2)
            _pump_until(tr, lambda: ch.connected)
            a.sendall(_frames(b"from-A-1", b"from-A-2"))
            a.sendall(frame_bytes(F_XP, 3, [b"A-torn" * 500])[:50])
            deadline = time.monotonic() + 5
            got = []
            while len(got) < 2 and time.monotonic() < deadline:
                rec = ch.read_next()
                if rec is not None:
                    got.append(rec)
            b = _connect_raw(tr, wid=3, attempt=2)   # reconnect
            _pump_until(tr, lambda: ch.reconnects >= 1)
            a.close()
            b.sendall(_frames(b"from-B-1"))          # seq restarts at 1
            deadline = time.monotonic() + 5
            while len(got) < 3 and time.monotonic() < deadline:
                rec = ch.read_next()
                if rec is not None:
                    got.append(rec)
            assert got == [b"from-A-1", b"from-A-2", b"from-B-1"]
            assert ch.torn_frames >= 1           # A's tail, counted at adopt
            b.close()
        finally:
            tr.close()

    def test_writer_reconnects_after_channel_drop(self):
        """NetWriter survives its connection being closed learner-side:
        backoff, reconnect, stream resumes.  (The ONE frame in flight at
        the drop may be lost or duplicated — the documented connection-
        loss contract — so the assertion is resumption, not exactly-once
        across the drop.)"""
        tr = NetTransport()
        try:
            ch = tr.make_channel(0, 0)
            w = NetWriter({"host": "127.0.0.1", "port": tr.port,
                           "token": tr.token, "wid": 0, "attempt": 0})
            assert w.write([b"first"], timeout=5)
            _pump_until(tr, lambda: ch.read_next() == b"first")
            # Drop the learner-side socket under the writer.
            with ch._send_lock:
                ch._retire_conn_locked()
            got = []
            deadline = time.monotonic() + 15
            i = 0
            while not got and time.monotonic() < deadline:
                assert w.write([b"resent-%d" % i], timeout=10)
                i += 1
                tr.pump()
                rec = ch.read_next()
                if rec is not None:
                    got.append(rec)
            assert got and got[0].startswith(b"resent-")
            assert w.reconnects >= 1
            w.close()
        finally:
            tr.close()

    def test_param_fanout_full_then_delta(self):
        tr = NetTransport()
        try:
            tr.make_channel(0, 0)
            w = NetWriter({"host": "127.0.0.1", "port": tr.port,
                           "token": tr.token, "wid": 0, "attempt": 0})
            assert w.write([b"hello-record"], timeout=5)  # connects
            rng = np.random.default_rng(2)
            blob1 = rng.integers(0, 255, 500_000, dtype=np.uint8).tobytes()
            blob2 = bytearray(blob1)
            blob2[100:132] = b"\x01" * 32
            blob2 = bytes(blob2)
            _pump_until(tr, lambda: tr.stats()["connections"] == 1)
            push1 = tr.set_params(blob1, 1)
            assert push1["full"] == 1 and push1["delta"] == 0
            deadline = time.monotonic() + 5
            while (w.latest_params() or (None, -1))[1] < 1 \
                    and time.monotonic() < deadline:
                w.pump_params()
                time.sleep(0.01)
            assert w.latest_params() == (blob1, 1)
            push2 = tr.set_params(blob2, 2)
            assert push2["delta"] == 1 and push2["full"] == 0
            assert push2["bytes"] < len(blob2) // 4   # delta-sized fan-out
            assert push2["fanout_ms"] >= 0
            deadline = time.monotonic() + 5
            while w.latest_params()[1] < 2 and time.monotonic() < deadline:
                w.pump_params()
                time.sleep(0.01)
            assert w.latest_params() == (blob2, 2)    # patched bit-exactly
            s = tr.stats()
            assert s["param_pushes"] == 2
            assert s["param_delta"] == 1 and s["param_full"] == 1
            w.close()
        finally:
            tr.close()


class TestPoolTcpBackend:
    """Pool-level discipline on the tcp backend, no real jax workers —
    the mirror of TestSigkillMidWrite.test_pool_salvage_gives_respawn
    _fresh_ring: committed records salvage into poll(), the torn tail is
    counted, the respawned incarnation gets a FRESH channel."""

    def _pool(self):
        from ape_x_dqn_tpu.config import ApexConfig
        from ape_x_dqn_tpu.runtime.process_actors import ProcessActorPool

        cfg = ApexConfig()
        cfg.network = "mlp"
        cfg.env.name = "chain:6"
        cfg.actor.mode = "process"
        cfg.actor.transport = "tcp"
        cfg.actor.num_workers = 1
        cfg.actor.num_actors = 2
        cfg.validate()
        return ProcessActorPool(cfg, num_workers=1, ring_bytes=1 << 16)

    def test_pool_salvage_counts_torn_and_retires_channel(self):
        from ape_x_dqn_tpu.runtime.transport import connect_channel

        pool = self._pool()
        try:
            assert pool.buffer is None      # params ride the connections
            pool._queues[0] = pool._ctx.Queue(maxsize=4)
            pool._rings[0] = pool._transport.make_channel(0, 0)
            spec = pool._transport.endpoint(pool._rings[0], 0, 0)
            w = connect_channel(spec)
            arrays = {"prio": np.ones(2, np.float32),
                      "obs": np.zeros((2, 3), np.uint8),
                      "action": np.zeros(2, np.int32),
                      "reward": np.zeros(2, np.float32),
                      "discount": np.ones(2, np.float32),
                      "next_obs": np.zeros((2, 3), np.uint8)}
            assert w.write(encode_chunk_parts(XP, 5, 2, arrays), timeout=5)
            assert w.write(encode_chunk_parts(XP, 6, 2, arrays), timeout=5)
            # Route the hello (poll() does this continuously in the real
            # pool); then the torn tail: a partial frame straight on the
            # writer's socket, then the "kill" (abrupt close).  Salvage
            # itself drains the kernel buffer — committed records first,
            # then the tear.
            _pump_until(pool._transport,
                        lambda: pool._rings[0].connected)
            time.sleep(0.3)
            w._sock.sendall(
                frame_bytes(F_XP, 3, [b"z" * 2048])[:100]
            )
            time.sleep(0.2)
            w._sock.close()
            time.sleep(0.2)
            pool._salvage_incarnation(0)
            salvaged = pool._salvaged
            assert len(salvaged) == 2
            stats = pool.transport_stats()
            assert stats["transport"] == "tcp"
            assert stats["torn_records"] == 1
            items = pool.poll(max_items=8)
            assert len(items) == 2
            assert pool.last_versions[0] == 6
            assert 0 not in pool._rings
        finally:
            pool.stop(join_timeout=1.0)

    def test_decoded_chunk_identical_to_shm_wire(self):
        """The tcp payload IS the ring record payload: decode_chunk sees
        byte-identical envelopes + arrays either way."""
        from ape_x_dqn_tpu.runtime.transport import connect_channel

        pool = self._pool()
        try:
            pool._queues[0] = pool._ctx.Queue(maxsize=4)
            pool._rings[0] = pool._transport.make_channel(0, 0)
            spec = pool._transport.endpoint(pool._rings[0], 0, 0)
            w = connect_channel(spec)
            rng = np.random.default_rng(7)
            arrays = {"prio": rng.random(3).astype(np.float32),
                      "obs": rng.integers(0, 255, (3, 4, 4, 1),
                                          dtype=np.uint8),
                      "action": np.arange(3, dtype=np.int32),
                      "reward": rng.normal(size=3).astype(np.float32),
                      "discount": np.full(3, 0.97, np.float32),
                      "next_obs": rng.integers(0, 255, (3, 4, 4, 1),
                                               dtype=np.uint8)}
            parts = encode_chunk_parts(XP, 11, 3, arrays, trace_id=0xF00)
            wire = b"".join(
                bytes(memoryview(p).cast("B")) if not isinstance(p, bytes)
                else p for p in parts
            )
            assert w.write(parts, timeout=5)
            deadline = time.monotonic() + 5
            rec = None
            while rec is None and time.monotonic() < deadline:
                pool._transport.pump()
                rec = pool._rings[0].read_next()
                time.sleep(0.01)
            assert rec == wire              # byte-for-byte the APXT record
            kind, ver, _, steps, _, _, _, tid, back = decode_chunk(rec)
            assert (kind, ver, steps, tid) == (XP, 11, 3, 0xF00)
            for k, v in arrays.items():
                np.testing.assert_array_equal(back[k], v)
            w.close()
        finally:
            pool.stop(join_timeout=1.0)


class TestRetiredChannelAccounting:
    def test_stats_survive_channel_retirement(self):
        """Cumulative transport counters must NOT vanish when a channel
        retires (respawn/stop): the final JSONL emit happens after
        pool.stop(), and a run that moved thousands of frames must not
        report frames_in=0 there (found driving the real CLI)."""
        tr = NetTransport()
        try:
            ch = tr.make_channel(0, 0)
            s = _connect_raw(tr)
            _pump_until(tr, lambda: ch.connected)
            s.sendall(_frames(b"a", b"b"))
            deadline = time.monotonic() + 5
            n = 0
            while n < 2 and time.monotonic() < deadline:
                if ch.read_next() is not None:
                    n += 1
            s.close()
            ch.close()
            tr.drop_channel(0, ch)
            stats = tr.stats()
            assert stats["expected"] == 0
            assert stats["frames_in"] == 2       # history folded, not lost
            assert stats["bytes_in"] > 0
        finally:
            tr.close()
        assert tr.stats()["frames_in"] == 2      # and survives close()


class TestBackoff:
    def test_backoff_doubles_and_caps(self):
        b = Backoff(base_s=0.1, max_s=0.4, jitter=0.0)
        assert b.ready()
        b.fail()
        assert not b.ready()
        t0 = time.monotonic()
        while not b.ready():
            time.sleep(0.005)
        assert 0.05 < time.monotonic() - t0 < 0.3
        b.fail(), b.fail(), b.fail(), b.fail()
        assert b._next_ok - time.monotonic() <= 0.45  # capped
        b.reset()
        assert b.ready()


class TestTransportBudgetPerHost:
    def test_shm_budget_is_local_host_only(self):
        from ape_x_dqn_tpu.config import ApexConfig, transport_budget

        cfg = ApexConfig()
        cfg.actor.xp_ring_bytes = 1 << 20
        b = transport_budget(cfg, num_workers=256)
        # Legacy arithmetic unchanged (the pre-seam pins hold)...
        assert b["shm_segments"] == 257
        assert b["ring_bytes_total"] == 256 << 20
        # ...and the breakdown makes the single-/dev/shm assumption
        # EXPLICIT: every ring byte on host 0, none anywhere else.
        assert b["transport"] == "shm" and b["hosts"] == 1
        assert len(b["per_host"]) == 1
        assert b["per_host"][0]["shm_bytes"] == 256 << 20
        assert b["per_host"][0]["sock_buf_bytes"] == 0

    def test_tcp_budget_splits_hosts_sockets_not_shm(self):
        from ape_x_dqn_tpu.config import ApexConfig, transport_budget

        cfg = ApexConfig()
        cfg.actor.transport = "tcp"
        cfg.actor.transport_hosts = 4
        cfg.actor.net_conn_buf_bytes = 1 << 20
        cfg.actor.xp_drain_budget_bytes = 64 << 20
        cfg.validate()
        b = transport_budget(cfg, num_workers=64)
        assert b["ring_bytes_total"] == 0 and b["shm_segments"] == 0
        hosts = b["per_host"]
        assert len(hosts) == 4
        assert sum(h["workers"] for h in hosts) == 64
        assert all(h["shm_bytes"] == 0 for h in hosts)  # no rings anywhere
        # Learner host carries a receive buffer per connection on top of
        # its local workers' send buffers; pure worker hosts only theirs.
        assert hosts[0]["sock_buf_bytes"] == (16 + 64) << 20
        assert hosts[1]["sock_buf_bytes"] == 16 << 20
        # Per-connection drain bound = sweep budget / fleet width.
        assert hosts[0]["conn_drain_budget_bytes"] == 1 << 20

    def test_tcp_knob_validation(self):
        from ape_x_dqn_tpu.config import ApexConfig

        cfg = ApexConfig()
        cfg.actor.transport = "bogus"
        with pytest.raises(ValueError, match="actor.transport"):
            cfg.validate()
        cfg = ApexConfig()
        cfg.actor.transport_hosts = 2      # shm cannot leave the host
        with pytest.raises(ValueError, match="transport_hosts"):
            cfg.validate()
        cfg = ApexConfig()
        cfg.actor.transport = "tcp"
        cfg.actor.transport_port = 99999
        with pytest.raises(ValueError, match="transport_port"):
            cfg.validate()
        cfg = ApexConfig()
        cfg.actor.transport = "tcp"
        cfg.actor.net_conn_buf_bytes = 1024
        with pytest.raises(ValueError, match="net_conn_buf_bytes"):
            cfg.validate()


class TestClockSkewClamp:
    def test_future_t_act_clamped_and_counted(self):
        """A remote host's monotonic clock running ahead stamps t_act in
        our future; the span is clamped at zero age and counted, never
        emitted negative."""
        from ape_x_dqn_tpu.obs.lineage import LineageTracker

        events = []
        lt = LineageTracker(
            64, emit=lambda name, **kw: events.append((name, kw))
        )
        skewed = time.monotonic() + 3600.0   # one hour ahead
        lt.on_ingest(np.arange(4), t_act=skewed, trace_id=77, wid=0)
        assert lt.clock_skew_clamped == 1
        lt.on_sample(np.arange(4))
        lt.on_trained(np.arange(4))
        assert lt.completed_count == 1
        (_, span), = events
        assert span["act_to_ingest_ms"] >= 0.0
        assert span["act_to_trained_ms"] >= 0.0
        assert span["t_act"] <= span["t_ingest"]
        assert lt.summary()["clock_skew_clamped"] == 1

    def test_sane_t_act_not_clamped(self):
        from ape_x_dqn_tpu.obs.lineage import LineageTracker

        lt = LineageTracker(64)
        lt.on_ingest(np.arange(4), t_act=time.monotonic() - 0.5,
                     trace_id=5, wid=0)
        assert lt.clock_skew_clamped == 0
