"""TCP experience-transport tests: the framing's adversarial decode
matrix (the socket mirror of tests/test_shm_ring.py's torn-tail matrix),
the param delta codec, channel hijack/reconnect interleaving, the
pool-level salvage discipline on the tcp backend, the per_host transport
budget, and the cross-host clock-skew clamp."""

import socket
import struct
import time

import numpy as np
import pytest

from ape_x_dqn_tpu.runtime.net import (
    _FRAME,
    _HELLO,
    _HELLO_EXT,
    _NET_MAGIC,
    _NET_VERSION,
    _NET_VERSION_EXT,
    CODEC_OFF,
    CODEC_ZLIB,
    F_XP,
    F_XPB,
    Backoff,
    FrameParser,
    NetTransport,
    NetWriter,
    apply_param_delta,
    build_param_delta,
    build_param_full,
    decode_batch,
    decode_xpb_payload,
    encode_batch,
    encode_xpb_payload,
    frame_bytes,
)
from ape_x_dqn_tpu.runtime.shm_ring import XP, decode_chunk, encode_chunk_parts


def _chunk_record(rows=8, n_step=3, seed=0, shape=(32, 32, 1),
                  version=1) -> bytes:
    """One dense XP record with the PRODUCTION n-step frame overlap
    (obs[i + n] == next_obs[i]) — the redundancy the wire dedup layer
    exists to remove."""
    rng = np.random.default_rng(seed)
    frames = rng.integers(0, 255, (rows + n_step, *shape), dtype=np.uint8)
    arrays = {
        "prio": (np.abs(rng.normal(size=rows)) + 0.1).astype(np.float32),
        "obs": frames[:rows],
        "action": rng.integers(0, 4, (rows,), dtype=np.int32),
        "reward": rng.normal(size=(rows,)).astype(np.float32),
        "discount": np.full((rows,), 0.97, np.float32),
        "next_obs": frames[n_step:rows + n_step],
    }
    parts = encode_chunk_parts(XP, version, rows, arrays)
    return b"".join(
        bytes(memoryview(p).cast("B")) if not isinstance(p, bytes) else p
        for p in parts
    )


def _frames(*payloads, start_seq=1):
    return b"".join(
        frame_bytes(F_XP, start_seq + i, [p]) for i, p in enumerate(payloads)
    )


class TestFrameParserAdversarial:
    """Truncation/corruption matrix: every fault is detected (parser
    error or pending tail), nothing invalid is ever yielded — the
    stream-level torn-ring-tail contract."""

    def test_roundtrip_and_order(self):
        p = FrameParser()
        p.feed(_frames(b"one", b"two", b"three"))
        got = [p.next() for _ in range(3)]
        assert [x[1] for x in got] == [b"one", b"two", b"three"]
        assert p.next() is None and p.error is None

    def test_truncation_mid_length_prefix(self):
        p = FrameParser()
        whole = _frames(b"committed", b"torn-after-this")
        p.feed(whole[:len(_frames(b"committed")) + 3])  # 3 B of next header
        assert p.next()[1] == b"committed"
        assert p.next() is None           # incomplete header: nothing out
        assert p.error is None
        assert p.pending() == 3           # the torn tail, detectable

    def test_truncation_mid_payload(self):
        p = FrameParser()
        whole = _frames(b"x" * 1000)
        p.feed(whole[:_FRAME.size + 137])
        assert p.next() is None
        assert p.pending() == _FRAME.size + 137

    def test_crc_bitflip_detected(self):
        buf = bytearray(_frames(b"a" * 600))
        buf[_FRAME.size + 300] ^= 0x40    # flip one payload bit
        p = FrameParser()
        p.feed(bytes(buf))
        assert p.next() is None
        assert p.error == "crc"
        p.feed(_frames(b"late", start_seq=2))
        assert p.next() is None           # dead stream yields nothing more

    def test_seq_skip_detected(self):
        p = FrameParser()
        p.feed(frame_bytes(F_XP, 1, [b"one"]))
        p.feed(frame_bytes(F_XP, 3, [b"skipped-two"]))
        assert p.next()[1] == b"one"
        assert p.next() is None
        assert p.error == "seq"

    def test_absurd_length_prefix_rejected(self):
        p = FrameParser()
        p.feed(_FRAME.pack(1 << 31, 0, 1, F_XP))
        assert p.next() is None
        assert p.error == "length"

    def test_byte_dribble_reassembles(self):
        """One byte at a time — frames only emerge complete and verified."""
        whole = _frames(b"dribbled-payload" * 10)
        p = FrameParser()
        out = []
        for i in range(len(whole)):
            p.feed(whole[i:i + 1])
            got = p.next()
            if got is not None:
                out.append(got[1])
        assert out == [b"dribbled-payload" * 10]


class TestParamDelta:
    def test_delta_roundtrip(self):
        rng = np.random.default_rng(0)
        prev = rng.integers(0, 255, 300_000, dtype=np.uint8).tobytes()
        new = bytearray(prev)
        new[1000:1032] = b"\x7f" * 32      # one dirty page
        new = bytes(new)
        d = build_param_delta(7, 6, prev, new)
        assert d is not None and len(d) < len(new) // 4
        version, base, blob = apply_param_delta(prev, d)
        assert (version, base) == (7, 6)
        assert blob == new

    def test_delta_falls_back_to_full_when_everything_moved(self):
        rng = np.random.default_rng(1)
        prev = rng.integers(0, 255, 100_000, dtype=np.uint8).tobytes()
        new = rng.integers(0, 255, 100_000, dtype=np.uint8).tobytes()
        assert build_param_delta(2, 1, prev, new) is None
        assert build_param_delta(2, 1, prev, prev + b"x") is None  # size

    def test_delta_crc_mismatch_raises(self):
        prev = bytes(200_000)
        new = bytearray(prev)
        new[5] = 1
        d = bytearray(build_param_delta(3, 2, prev, bytes(new)))
        d[-1] ^= 0x01                      # corrupt a patched page byte
        with pytest.raises(ValueError, match="crc"):
            apply_param_delta(prev, bytes(d))
        with pytest.raises(ValueError):    # wrong baseline blob
            apply_param_delta(bytes(199_999), bytes(d))

    def test_full_frame_layout(self):
        payload = build_param_full(9, b"blob-bytes")
        (v,) = struct.unpack_from("<q", payload, 0)
        assert v == 9 and payload[8:] == b"blob-bytes"


def _hello(tr, wid=0, attempt=0, token=None, version=_NET_VERSION,
           ext=b""):
    return _HELLO.pack(_NET_MAGIC, version, wid, attempt,
                       tr.token if token is None else token) + ext


def _connect_raw(tr, **kw):
    s = socket.create_connection(("127.0.0.1", tr.port), timeout=5)
    s.sendall(_hello(tr, **kw))
    return s


def _pump_until(tr, cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        tr.pump()
        if cond():
            return
        time.sleep(0.01)
    raise TimeoutError("condition not reached")


class TestNetTransportChannel:
    def test_handshake_routes_and_reads(self):
        tr = NetTransport()
        try:
            ch = tr.make_channel(0, 0)
            s = _connect_raw(tr)
            _pump_until(tr, lambda: ch.connected)
            s.sendall(_frames(b"r1", b"r2"))
            got = []
            deadline = time.monotonic() + 5
            while len(got) < 2 and time.monotonic() < deadline:
                rec = ch.read_next()
                if rec is not None:
                    got.append(rec)
            assert got == [b"r1", b"r2"]
            assert ch.committed == 2 and not ch.torn_tail()
            s.close()
        finally:
            tr.close()

    def test_bad_token_and_stale_attempt_rejected(self):
        tr = NetTransport()
        try:
            ch = tr.make_channel(0, 1)
            s1 = _connect_raw(tr, token=12345)       # wrong run
            s2 = _connect_raw(tr, attempt=0)         # stale incarnation
            s3 = _connect_raw(tr, wid=9)             # unknown worker
            _pump_until(tr, lambda: tr.rejects >= 3)
            assert not ch.connected
            for s in (s1, s2, s3):
                s.close()
        finally:
            tr.close()

    def test_disconnect_mid_payload_is_torn_never_delivered(self):
        tr = NetTransport()
        try:
            ch = tr.make_channel(0, 0)
            s = _connect_raw(tr)
            _pump_until(tr, lambda: ch.connected)
            s.sendall(_frames(b"whole-record"))
            partial = frame_bytes(F_XP, 2, [b"y" * 4096])[:200]
            s.sendall(partial)
            time.sleep(0.2)
            s.close()
            # Salvage sweep: the committed record arrives, the torn tail
            # never does, and the tear is counted.
            deadline = time.monotonic() + 5
            got = []
            while time.monotonic() < deadline:
                rec = ch.read_next()
                if rec is not None:
                    got.append(rec)
                if not ch.connected and ch.read_next() is None:
                    break
                time.sleep(0.01)
            assert got == [b"whole-record"]
            assert ch.torn_tail() and ch.torn_live >= 1
        finally:
            tr.close()

    def test_interleaved_reconnect_fresh_seq_stream(self):
        """Connection A delivers, dies mid-frame; connection B (same
        worker, fresh hello) adopts with a FRESH seq stream — its frames
        deliver, A's torn tail is counted, nothing interleaves."""
        tr = NetTransport()
        try:
            ch = tr.make_channel(3, 2)
            a = _connect_raw(tr, wid=3, attempt=2)
            _pump_until(tr, lambda: ch.connected)
            a.sendall(_frames(b"from-A-1", b"from-A-2"))
            a.sendall(frame_bytes(F_XP, 3, [b"A-torn" * 500])[:50])
            deadline = time.monotonic() + 5
            got = []
            while len(got) < 2 and time.monotonic() < deadline:
                rec = ch.read_next()
                if rec is not None:
                    got.append(rec)
            b = _connect_raw(tr, wid=3, attempt=2)   # reconnect
            _pump_until(tr, lambda: ch.reconnects >= 1)
            a.close()
            b.sendall(_frames(b"from-B-1"))          # seq restarts at 1
            deadline = time.monotonic() + 5
            while len(got) < 3 and time.monotonic() < deadline:
                rec = ch.read_next()
                if rec is not None:
                    got.append(rec)
            assert got == [b"from-A-1", b"from-A-2", b"from-B-1"]
            assert ch.torn_frames >= 1           # A's tail, counted at adopt
            b.close()
        finally:
            tr.close()

    def test_writer_reconnects_after_channel_drop(self):
        """NetWriter survives its connection being closed learner-side:
        backoff, reconnect, stream resumes.  (The ONE frame in flight at
        the drop may be lost or duplicated — the documented connection-
        loss contract — so the assertion is resumption, not exactly-once
        across the drop.)"""
        tr = NetTransport()
        try:
            ch = tr.make_channel(0, 0)
            w = NetWriter({"host": "127.0.0.1", "port": tr.port,
                           "token": tr.token, "wid": 0, "attempt": 0})
            assert w.write([b"first"], timeout=5)
            _pump_until(tr, lambda: ch.read_next() == b"first")
            # Drop the learner-side socket under the writer.
            with ch._send_lock:
                ch._retire_conn_locked()
            got = []
            deadline = time.monotonic() + 15
            i = 0
            while not got and time.monotonic() < deadline:
                assert w.write([b"resent-%d" % i], timeout=10)
                i += 1
                tr.pump()
                rec = ch.read_next()
                if rec is not None:
                    got.append(rec)
            assert got and got[0].startswith(b"resent-")
            assert w.reconnects >= 1
            w.close()
        finally:
            tr.close()

    def test_param_fanout_full_then_delta(self):
        tr = NetTransport()
        try:
            tr.make_channel(0, 0)
            w = NetWriter({"host": "127.0.0.1", "port": tr.port,
                           "token": tr.token, "wid": 0, "attempt": 0})
            assert w.write([b"hello-record"], timeout=5)  # connects
            rng = np.random.default_rng(2)
            blob1 = rng.integers(0, 255, 500_000, dtype=np.uint8).tobytes()
            blob2 = bytearray(blob1)
            blob2[100:132] = b"\x01" * 32
            blob2 = bytes(blob2)
            _pump_until(tr, lambda: tr.stats()["connections"] == 1)
            push1 = tr.set_params(blob1, 1)
            assert push1["full"] == 1 and push1["delta"] == 0
            deadline = time.monotonic() + 5
            while (w.latest_params() or (None, -1))[1] < 1 \
                    and time.monotonic() < deadline:
                w.pump_params()
                time.sleep(0.01)
            assert w.latest_params() == (blob1, 1)
            push2 = tr.set_params(blob2, 2)
            assert push2["delta"] == 1 and push2["full"] == 0
            assert push2["bytes"] < len(blob2) // 4   # delta-sized fan-out
            assert push2["fanout_ms"] >= 0
            deadline = time.monotonic() + 5
            while w.latest_params()[1] < 2 and time.monotonic() < deadline:
                w.pump_params()
                time.sleep(0.01)
            assert w.latest_params() == (blob2, 2)    # patched bit-exactly
            s = tr.stats()
            assert s["param_pushes"] == 2
            assert s["param_delta"] == 1 and s["param_full"] == 1
            w.close()
        finally:
            tr.close()


class TestPoolTcpBackend:
    """Pool-level discipline on the tcp backend, no real jax workers —
    the mirror of TestSigkillMidWrite.test_pool_salvage_gives_respawn
    _fresh_ring: committed records salvage into poll(), the torn tail is
    counted, the respawned incarnation gets a FRESH channel."""

    def _pool(self):
        from ape_x_dqn_tpu.config import ApexConfig
        from ape_x_dqn_tpu.runtime.process_actors import ProcessActorPool

        cfg = ApexConfig()
        cfg.network = "mlp"
        cfg.env.name = "chain:6"
        cfg.actor.mode = "process"
        cfg.actor.transport = "tcp"
        cfg.actor.num_workers = 1
        cfg.actor.num_actors = 2
        cfg.validate()
        return ProcessActorPool(cfg, num_workers=1, ring_bytes=1 << 16)

    def test_pool_salvage_counts_torn_and_retires_channel(self):
        from ape_x_dqn_tpu.runtime.transport import connect_channel

        pool = self._pool()
        try:
            assert pool.buffer is None      # params ride the connections
            pool._queues[0] = pool._ctx.Queue(maxsize=4)
            pool._rings[0] = pool._transport.make_channel(0, 0)
            spec = pool._transport.endpoint(pool._rings[0], 0, 0)
            w = connect_channel(spec)
            arrays = {"prio": np.ones(2, np.float32),
                      "obs": np.zeros((2, 3), np.uint8),
                      "action": np.zeros(2, np.int32),
                      "reward": np.zeros(2, np.float32),
                      "discount": np.ones(2, np.float32),
                      "next_obs": np.zeros((2, 3), np.uint8)}
            assert w.write(encode_chunk_parts(XP, 5, 2, arrays), timeout=5)
            assert w.write(encode_chunk_parts(XP, 6, 2, arrays), timeout=5)
            # Route the hello (poll() does this continuously in the real
            # pool); then the torn tail: a partial frame straight on the
            # writer's socket, then the "kill" (abrupt close).  Salvage
            # itself drains the kernel buffer — committed records first,
            # then the tear.
            _pump_until(pool._transport,
                        lambda: pool._rings[0].connected)
            time.sleep(0.3)
            w._sock.sendall(
                frame_bytes(F_XP, 3, [b"z" * 2048])[:100]
            )
            time.sleep(0.2)
            w._sock.close()
            time.sleep(0.2)
            pool._salvage_incarnation(0)
            salvaged = pool._salvaged
            assert len(salvaged) == 2
            stats = pool.transport_stats()
            assert stats["transport"] == "tcp"
            assert stats["torn_records"] == 1
            items = pool.poll(max_items=8)
            assert len(items) == 2
            assert pool.last_versions[0] == 6
            assert 0 not in pool._rings
        finally:
            pool.stop(join_timeout=1.0)

    def test_decoded_chunk_identical_to_shm_wire(self):
        """The tcp payload IS the ring record payload: decode_chunk sees
        byte-identical envelopes + arrays either way."""
        from ape_x_dqn_tpu.runtime.transport import connect_channel

        pool = self._pool()
        try:
            pool._queues[0] = pool._ctx.Queue(maxsize=4)
            pool._rings[0] = pool._transport.make_channel(0, 0)
            spec = pool._transport.endpoint(pool._rings[0], 0, 0)
            w = connect_channel(spec)
            rng = np.random.default_rng(7)
            arrays = {"prio": rng.random(3).astype(np.float32),
                      "obs": rng.integers(0, 255, (3, 4, 4, 1),
                                          dtype=np.uint8),
                      "action": np.arange(3, dtype=np.int32),
                      "reward": rng.normal(size=3).astype(np.float32),
                      "discount": np.full(3, 0.97, np.float32),
                      "next_obs": rng.integers(0, 255, (3, 4, 4, 1),
                                               dtype=np.uint8)}
            parts = encode_chunk_parts(XP, 11, 3, arrays, trace_id=0xF00)
            wire = b"".join(
                bytes(memoryview(p).cast("B")) if not isinstance(p, bytes)
                else p for p in parts
            )
            assert w.write(parts, timeout=5)
            deadline = time.monotonic() + 5
            rec = None
            while rec is None and time.monotonic() < deadline:
                pool._transport.pump()
                rec = pool._rings[0].read_next()
                time.sleep(0.01)
            assert rec == wire              # byte-for-byte the APXT record
            kind, ver, _, steps, _, _, _, tid, back = decode_chunk(rec)
            assert (kind, ver, steps, tid) == (XP, 11, 3, 0xF00)
            for k, v in arrays.items():
                np.testing.assert_array_equal(back[k], v)
            w.close()
        finally:
            pool.stop(join_timeout=1.0)


class TestRetiredChannelAccounting:
    def test_stats_survive_channel_retirement(self):
        """Cumulative transport counters must NOT vanish when a channel
        retires (respawn/stop): the final JSONL emit happens after
        pool.stop(), and a run that moved thousands of frames must not
        report frames_in=0 there (found driving the real CLI)."""
        tr = NetTransport()
        try:
            ch = tr.make_channel(0, 0)
            s = _connect_raw(tr)
            _pump_until(tr, lambda: ch.connected)
            s.sendall(_frames(b"a", b"b"))
            deadline = time.monotonic() + 5
            n = 0
            while n < 2 and time.monotonic() < deadline:
                if ch.read_next() is not None:
                    n += 1
            s.close()
            ch.close()
            tr.drop_channel(0, ch)
            stats = tr.stats()
            assert stats["expected"] == 0
            assert stats["frames_in"] == 2       # history folded, not lost
            assert stats["bytes_in"] > 0
        finally:
            tr.close()
        assert tr.stats()["frames_in"] == 2      # and survives close()


class TestBackoff:
    def test_backoff_doubles_and_caps(self):
        b = Backoff(base_s=0.1, max_s=0.4, jitter=0.0)
        assert b.ready()
        b.fail()
        assert not b.ready()
        t0 = time.monotonic()
        while not b.ready():
            time.sleep(0.005)
        assert 0.05 < time.monotonic() - t0 < 0.3
        b.fail(), b.fail(), b.fail(), b.fail()
        assert b._next_ok - time.monotonic() <= 0.45  # capped
        b.reset()
        assert b.ready()


class TestTransportBudgetPerHost:
    def test_shm_budget_is_local_host_only(self):
        from ape_x_dqn_tpu.config import ApexConfig, transport_budget

        cfg = ApexConfig()
        cfg.actor.xp_ring_bytes = 1 << 20
        b = transport_budget(cfg, num_workers=256)
        # Legacy arithmetic unchanged (the pre-seam pins hold)...
        assert b["shm_segments"] == 257
        assert b["ring_bytes_total"] == 256 << 20
        # ...and the breakdown makes the single-/dev/shm assumption
        # EXPLICIT: every ring byte on host 0, none anywhere else.
        assert b["transport"] == "shm" and b["hosts"] == 1
        assert len(b["per_host"]) == 1
        assert b["per_host"][0]["shm_bytes"] == 256 << 20
        assert b["per_host"][0]["sock_buf_bytes"] == 0

    def test_tcp_budget_splits_hosts_sockets_not_shm(self):
        from ape_x_dqn_tpu.config import ApexConfig, transport_budget

        cfg = ApexConfig()
        cfg.actor.transport = "tcp"
        cfg.actor.transport_hosts = 4
        cfg.actor.net_conn_buf_bytes = 1 << 20
        cfg.actor.xp_drain_budget_bytes = 64 << 20
        cfg.validate()
        b = transport_budget(cfg, num_workers=64)
        assert b["ring_bytes_total"] == 0 and b["shm_segments"] == 0
        hosts = b["per_host"]
        assert len(hosts) == 4
        assert sum(h["workers"] for h in hosts) == 64
        assert all(h["shm_bytes"] == 0 for h in hosts)  # no rings anywhere
        # Learner host carries a receive buffer per connection on top of
        # its local workers' send buffers; pure worker hosts only theirs.
        assert hosts[0]["sock_buf_bytes"] == (16 + 64) << 20
        assert hosts[1]["sock_buf_bytes"] == 16 << 20
        # Per-connection drain bound = sweep budget / fleet width.
        assert hosts[0]["conn_drain_budget_bytes"] == 1 << 20

    def test_wire_efficiency_terms_and_legacy_keys_pinned(self):
        """The codec/coalesce buffer terms (ISSUE 10 satellite): staging
        on each worker's host + a per-connection reassembly window and
        codec scratch on the learner host — and every LEGACY key at the
        same settings is byte-for-byte what it was before the layers
        existed (shm and plain tcp both report the new terms as 0)."""
        from ape_x_dqn_tpu.config import ApexConfig, transport_budget

        cfg = ApexConfig()
        cfg.actor.transport = "tcp"
        cfg.actor.transport_hosts = 2
        cfg.actor.net_conn_buf_bytes = 1 << 20
        cfg.actor.xp_drain_budget_bytes = 64 << 20
        cfg.actor.net_codec = "zlib"
        cfg.actor.net_coalesce_bytes = 2 << 20
        cfg.validate()
        b = transport_budget(cfg, num_workers=8)
        hosts = b["per_host"]
        # Legacy keys unchanged by the new layers.
        assert b["ring_bytes_total"] == 0 and b["shm_segments"] == 0
        assert b["fds_per_worker"] == 5
        assert hosts[0]["sock_buf_bytes"] == (4 + 8) << 20
        assert hosts[1]["sock_buf_bytes"] == 4 << 20
        assert hosts[0]["conn_drain_budget_bytes"] == 8 << 20
        # New terms: 4 local workers' staging + 8 connections' windows
        # on host 0; workers' staging only on host 1.
        assert hosts[0]["coalesce_buf_bytes"] == (4 + 8) * (2 << 20)
        assert hosts[1]["coalesce_buf_bytes"] == 4 * (2 << 20)
        # Codec scratch tracks the coalesce budget when compression is on.
        assert hosts[0]["codec_scratch_bytes"] == (4 + 8) * (2 << 20)
        assert hosts[1]["codec_scratch_bytes"] == 4 * (2 << 20)
        # Codec off, coalesce off => both terms vanish; legacy unchanged.
        cfg.actor.net_codec = "off"
        cfg.actor.net_coalesce_bytes = 0
        b2 = transport_budget(cfg, num_workers=8)
        assert all(h["coalesce_buf_bytes"] == 0 for h in b2["per_host"])
        assert all(h["codec_scratch_bytes"] == 0 for h in b2["per_host"])
        assert b2["per_host"][0]["sock_buf_bytes"] == (4 + 8) << 20
        # Codec-only wires still budget inflate/deflate scratch (floored).
        cfg.actor.net_codec = "auto"
        b3 = transport_budget(cfg, num_workers=8)
        assert b3["per_host"][1]["codec_scratch_bytes"] == 4 << 20
        assert b3["per_host"][1]["coalesce_buf_bytes"] == 0
        # The shm backend never grows these terms.
        cfg2 = ApexConfig()
        b4 = transport_budget(cfg2, num_workers=4)
        assert b4["per_host"][0]["coalesce_buf_bytes"] == 0
        assert b4["per_host"][0]["codec_scratch_bytes"] == 0
        assert b4["shm_segments"] == 5      # legacy pin: rings + params

    def test_wire_knob_validation(self):
        from ape_x_dqn_tpu.config import ApexConfig

        cfg = ApexConfig()
        cfg.actor.transport = "tcp"
        cfg.actor.net_codec = "gzip9"
        with pytest.raises(ValueError, match="net_codec"):
            cfg.validate()
        cfg = ApexConfig()
        cfg.actor.transport = "tcp"
        cfg.actor.net_coalesce_bytes = 512
        with pytest.raises(ValueError, match="net_coalesce_bytes"):
            cfg.validate()
        cfg = ApexConfig()
        cfg.actor.transport = "tcp"
        cfg.actor.net_coalesce_wait_ms = -1.0
        with pytest.raises(ValueError, match="net_coalesce_wait_ms"):
            cfg.validate()
        cfg = ApexConfig()                   # shm cannot use the layers
        cfg.actor.net_codec = "zlib"
        with pytest.raises(ValueError, match="transport=tcp"):
            cfg.validate()

    def test_tcp_knob_validation(self):
        from ape_x_dqn_tpu.config import ApexConfig

        cfg = ApexConfig()
        cfg.actor.transport = "bogus"
        with pytest.raises(ValueError, match="actor.transport"):
            cfg.validate()
        cfg = ApexConfig()
        cfg.actor.transport_hosts = 2      # shm cannot leave the host
        with pytest.raises(ValueError, match="transport_hosts"):
            cfg.validate()
        cfg = ApexConfig()
        cfg.actor.transport = "tcp"
        cfg.actor.transport_port = 99999
        with pytest.raises(ValueError, match="transport_port"):
            cfg.validate()
        cfg = ApexConfig()
        cfg.actor.transport = "tcp"
        cfg.actor.net_conn_buf_bytes = 1024
        with pytest.raises(ValueError, match="net_conn_buf_bytes"):
            cfg.validate()


class TestBatchCodec:
    """The F_XPB container in isolation: bit-exact reconstruction, dedup
    economics on n-step-overlapped chunks, codec honesty."""

    def test_envelope_layout_mirrors_shm_ring(self):
        """net.py re-declares the record envelope + APXT prefix so it
        stays standalone-loadable; the layouts must never drift."""
        from ape_x_dqn_tpu.runtime import net, shm_ring

        assert net._XP_ENVELOPE.size == shm_ring._MSG.size
        assert net._XP_ENVELOPE.format == shm_ring._MSG.format
        assert net._APXT_PREFIX.size == shm_ring._APXT_PREFIX.size
        assert net._APXT_MAGIC == shm_ring._APXT_MAGIC

    def test_roundtrip_bit_exact_with_and_without_dedup(self):
        recs = [_chunk_record(seed=s) for s in range(3)]
        for dedup in (True, False):
            body, _ = encode_batch(recs, dedup=dedup)
            assert decode_batch(body) == recs

    def test_dedup_halves_nstep_overlapped_chunks(self):
        rec = _chunk_record(rows=16, n_step=3)
        body, st = encode_batch([rec], dedup=True)
        # 16 obs + 16 next_obs frames, 13 of them window-duplicates.
        assert st["dedup_hits"] == 13
        assert len(body) < 0.65 * len(rec)
        # Identical records across the window dedup almost entirely.
        body2, st2 = encode_batch([rec, rec], dedup=True)
        assert st2["dedup_hits"] > st["dedup_hits"]
        assert len(body2) < len(body) + 0.2 * len(rec)

    def test_zlib_only_sticks_when_it_shrinks(self):
        rng = np.random.default_rng(3)
        incompressible = bytes(rng.integers(0, 255, 50_000, dtype=np.uint8))
        p, st = encode_xpb_payload([incompressible], codec=CODEC_ZLIB,
                                   dedup=False)
        assert st["compressed"] is False and p[0] == CODEC_OFF
        compressible = bytes(1000) * 50
        p2, st2 = encode_xpb_payload([compressible], codec=CODEC_ZLIB,
                                     dedup=False)
        assert st2["compressed"] is True and p2[0] == CODEC_ZLIB
        assert len(p2) < len(compressible) // 10
        assert decode_xpb_payload(p2) == [compressible]

    def test_codec_off_payload_never_compressed(self):
        p, st = encode_xpb_payload([bytes(4096)], codec=CODEC_OFF,
                                   dedup=False)
        assert p[0] == CODEC_OFF and st["compressed"] is False


class TestBatchAdversarial:
    """The new encode layers' decode matrix: every malformation raises
    (unit level) / counts torn + retires the connection (wire level) —
    nothing invalid is EVER ingested."""

    def test_ref_outside_window_raises(self):
        rec = b"x" * 500
        body, _ = encode_batch([rec], dedup=False)
        # Hand-craft a batch whose ref reaches past the decoded stream.
        import struct as _s

        evil = (_s.pack("<I", 1) + _s.pack("<I", 600)
                + _s.pack("<BI", 0, 500) + rec
                + _s.pack("<BIQ", 1, 100, 450))  # 450+100 > 500 decoded
        with pytest.raises(ValueError, match="window"):
            decode_batch(evil)

    def test_length_table_mismatch_raises(self):
        import struct as _s

        short = _s.pack("<I", 1) + _s.pack("<I", 100) \
            + _s.pack("<BI", 0, 40) + b"y" * 40
        with pytest.raises(ValueError, match="shorter"):
            decode_batch(short)
        over = _s.pack("<I", 1) + _s.pack("<I", 10) \
            + _s.pack("<BI", 0, 40) + b"y" * 40
        with pytest.raises(ValueError, match="overrun"):
            decode_batch(over)

    def test_bad_op_and_truncations_raise(self):
        import struct as _s

        with pytest.raises(ValueError):
            decode_batch(b"")                       # no count
        with pytest.raises(ValueError, match="length table"):
            decode_batch(_s.pack("<I", 4) + b"\x00" * 4)
        with pytest.raises(ValueError, match="op"):
            decode_batch(_s.pack("<I", 1) + _s.pack("<I", 1) + b"\x07")
        with pytest.raises(ValueError, match="truncated literal"):
            decode_batch(_s.pack("<I", 1) + _s.pack("<I", 50)
                         + _s.pack("<BI", 0, 50) + b"z" * 10)

    def test_decompress_fault_raises(self):
        good, st = encode_xpb_payload([bytes(1000) * 20], codec=CODEC_ZLIB,
                                      dedup=False)
        assert st["compressed"]
        # Deflate streams carry padding/unused-table bits, so one flip
        # can be semantically invisible — the CONTRACT is that every flip
        # either raises or decodes bit-identical (harmless): corrupt
        # output can never come back verified.
        raised = 0
        for pos in range(1, len(good)):
            bad = bytearray(good)
            bad[pos] ^= 0x10
            try:
                out = decode_xpb_payload(bytes(bad))
            except ValueError:
                raised += 1
                continue
            assert out == [bytes(1000) * 20], f"corrupt decode at {pos}"
        assert raised >= 1                  # consequential flips detected
        with pytest.raises(ValueError, match="truncated"):
            decode_xpb_payload(good[:len(good) // 2])   # truncated stream
        with pytest.raises(ValueError, match="negotiated off"):
            decode_xpb_payload(good, allow_zlib=False)
        with pytest.raises(ValueError, match="unknown codec"):
            decode_xpb_payload(b"\x07" + good[1:])

    def test_truncated_coalesced_frame_mid_record_is_torn(self):
        """A batch frame cut mid-record at disconnect: the committed
        frame before it delivers, the torn batch never yields ANY of its
        records, the tear is counted."""
        tr = NetTransport(codec="zlib")
        try:
            ch = tr.make_channel(0, 0)
            s = _connect_raw(tr, version=_NET_VERSION_EXT,
                             ext=_HELLO_EXT.pack(CODEC_ZLIB, 1))
            _pump_until(tr, lambda: ch.connected)
            whole, _ = encode_xpb_payload([b"first-record"], dedup=False)
            s.sendall(frame_bytes(F_XPB, 1, [whole]))
            batch2, _ = encode_xpb_payload(
                [b"second-record", b"third-record"], dedup=False
            )
            torn = frame_bytes(F_XPB, 2, [batch2])
            s.sendall(torn[:len(torn) - 7])   # cut inside the last record
            time.sleep(0.2)
            s.close()
            got = []
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                rec = ch.read_next()
                if rec is not None:
                    got.append(rec)
                elif not ch.connected:
                    break
                time.sleep(0.01)
            assert got == [b"first-record"]
            assert ch.torn_tail() and tr.stats()["torn_frames"] >= 1
        finally:
            tr.close()

    def test_bitflip_inside_compressed_payload_torn_and_retired(self):
        """The frame CRC covers the ENCODED bytes; a flip the sampled
        window missed still dies in zlib's adler32 — counted torn,
        nothing ingested, connection retired."""
        tr = NetTransport(codec="zlib")
        try:
            ch = tr.make_channel(0, 0)
            s = _connect_raw(tr, version=_NET_VERSION_EXT,
                             ext=_HELLO_EXT.pack(CODEC_ZLIB, 1))
            _pump_until(tr, lambda: ch.connected)
            payload, st = encode_xpb_payload(
                [bytes(8192) * 4, bytes(range(256)) * 64], dedup=False,
                codec=CODEC_ZLIB,
            )
            assert st["compressed"]
            # Pick a flip the codec layer provably rejects (deflate
            # padding bits make some flips invisible — harmless ones).
            evil = None
            for pos in range(len(payload) // 2, len(payload)):
                cand = bytearray(payload)
                cand[pos] ^= 0x20
                try:
                    decode_xpb_payload(bytes(cand))
                except ValueError:
                    evil = bytes(cand)
                    break
            assert evil is not None
            # Re-framed with a CORRECT crc over the flipped bytes: the
            # frame layer verifies clean, the codec layer must catch it.
            s.sendall(frame_bytes(F_XPB, 1, [evil]))
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                assert ch.read_next() is None      # nothing EVER delivered
                if tr.stats()["torn_frames"] >= 1:
                    break
                time.sleep(0.01)
            assert tr.stats()["torn_frames"] >= 1
            assert ch.committed == 0 and not ch.connected
            s.close()
        finally:
            tr.close()

    def test_dedup_ref_out_of_window_torn_on_the_wire(self):
        import struct as _s

        tr = NetTransport()
        try:
            ch = tr.make_channel(0, 0)
            s = _connect_raw(tr, version=_NET_VERSION_EXT,
                             ext=_HELLO_EXT.pack(CODEC_OFF, 1))
            _pump_until(tr, lambda: ch.connected)
            evil_body = (_s.pack("<I", 1) + _s.pack("<I", 64)
                         + _s.pack("<BIQ", 1, 64, 0))  # ref, empty window
            s.sendall(frame_bytes(F_XPB, 1, [b"\x00" + evil_body]))
            _pump_until(tr, lambda: (ch.read_next(), False)[1]
                        or tr.stats()["torn_frames"] >= 1)
            assert ch.committed == 0 and not ch.connected
            s.close()
        finally:
            tr.close()

    def test_codec_mismatch_hello_rejected(self):
        """A writer proposing zlib against an off-codec transport is
        refused AT THE HANDSHAKE — no framing state, no channel adopt."""
        tr = NetTransport(codec="off")
        try:
            ch = tr.make_channel(0, 0)
            s = _connect_raw(tr, version=_NET_VERSION_EXT,
                             ext=_HELLO_EXT.pack(CODEC_ZLIB, 1))
            _pump_until(tr, lambda: tr.rejects >= 1)
            assert tr.codec_rejects == 1
            assert not ch.connected
            # An off-codec v2 hello against the same transport is fine.
            s2 = _connect_raw(tr, version=_NET_VERSION_EXT,
                              ext=_HELLO_EXT.pack(CODEC_OFF, 1))
            _pump_until(tr, lambda: ch.connected)
            assert tr.stats()["codec_rejects"] == 1
            s.close()
            s2.close()
        finally:
            tr.close()

    def test_compressed_batch_on_off_negotiated_connection_torn(self):
        """Even a VALID zlib batch is a protocol violation on a
        connection whose hello negotiated codec off."""
        tr = NetTransport(codec="zlib")
        try:
            ch = tr.make_channel(0, 0)
            s = _connect_raw(tr, version=_NET_VERSION_EXT,
                             ext=_HELLO_EXT.pack(CODEC_OFF, 1))
            _pump_until(tr, lambda: ch.connected)
            payload, st = encode_xpb_payload([bytes(4096) * 8],
                                             codec=CODEC_ZLIB, dedup=False)
            assert st["compressed"]
            s.sendall(frame_bytes(F_XPB, 1, [payload]))
            _pump_until(tr, lambda: (ch.read_next(), False)[1]
                        or tr.stats()["torn_frames"] >= 1)
            assert ch.committed == 0
            s.close()
        finally:
            tr.close()


class TestWireEfficiencyEndToEnd:
    def _writer(self, tr, **wire):
        spec = {"host": "127.0.0.1", "port": tr.port, "token": tr.token,
                "wid": 0, "attempt": 0, **wire}
        return NetWriter(spec)

    def test_coalesced_dedup_zlib_bit_exact_and_ratio(self):
        """The full stack on: many records per wire frame, bit-exact
        reconstruction, wire bytes < logical bytes, occupancy > 1."""
        tr = NetTransport(codec="zlib")
        try:
            ch = tr.make_channel(0, 0)
            w = self._writer(tr, codec="zlib", coalesce=4 << 20,
                             coalesce_wait_ms=10_000.0, dedup=True)
            recs = [_chunk_record(seed=s) for s in range(4)]
            parts_sets = [[r] for r in recs]
            for ps in parts_sets:
                assert w.write(ps, timeout=5)
            assert w.flush(timeout=5)
            got = []
            deadline = time.monotonic() + 5
            while len(got) < 4 and time.monotonic() < deadline:
                tr.pump()
                rec = ch.read_next()
                if rec is not None:
                    got.append(rec)
                else:
                    time.sleep(0.005)
            assert got == recs                     # bit-exact ingest
            s = tr.stats()
            assert s["torn_frames"] == 0
            assert s["coalesced_frames_in"] == 1
            assert s["records_per_frame"] == 4.0
            assert s["logical_bytes_in"] == sum(len(r) for r in recs)
            assert s["wire_over_logical"] < 1.0    # dedup+codec winning
            assert s["codec_ms"] >= 0.0
            assert w.records_written == 4 and w.flushes == 1
            assert w.dedup_ref_bytes > 0
            w.close()
        finally:
            tr.close()

    def test_codec_off_coalesce_off_wire_bit_identical_to_v1(self):
        """The acceptance pin: a default-spec writer puts EXACTLY the v1
        bytes on the wire — v1 hello, one F_XP frame per record, same
        header/crc arithmetic as before the wire-efficiency layers."""
        import socket as socket_mod

        srv = socket_mod.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        try:
            w = NetWriter({"host": "127.0.0.1",
                           "port": srv.getsockname()[1],
                           "token": 77, "wid": 3, "attempt": 1})
            payloads = [b"alpha-record", b"beta" * 600]
            ok = []
            import threading as _t

            def _feed():
                ok.append(all(w.write([p], timeout=5) for p in payloads))

            th = _t.Thread(target=_feed)
            th.start()
            conn, _ = srv.accept()
            conn.settimeout(5)
            expect = _HELLO.pack(_NET_MAGIC, _NET_VERSION, 3, 1, 77) \
                + frame_bytes(F_XP, 1, [payloads[0]]) \
                + frame_bytes(F_XP, 2, [payloads[1]])
            raw = b""
            while len(raw) < len(expect):
                raw += conn.recv(len(expect) - len(raw))
            th.join(timeout=5)
            assert ok == [True]
            assert raw == expect
            assert not w._features and w.flushes == 0
            w.close()
            conn.close()
        finally:
            srv.close()

    def test_quantum_flush_and_close_flush(self):
        """Records never rot in the coalescing buffer: an explicit
        flush() pushes a partial batch, and close() flushes the rest."""
        tr = NetTransport(codec="zlib")
        try:
            ch = tr.make_channel(0, 0)
            w = self._writer(tr, codec="zlib", coalesce=64 << 20,
                             coalesce_wait_ms=10_000.0)
            assert w.write([b"sits-in-the-buffer"], timeout=5)
            assert ch.read_next() is None
            assert w.flush(timeout=5)
            _pump_until(tr, lambda: ch.read_next() == b"sits-in-the-buffer")
            assert w.write([b"flushed-at-close"], timeout=5)
            w.close()
            _pump_until(tr, lambda: ch.read_next() == b"flushed-at-close")
        finally:
            tr.close()

    def test_auto_codec_gates_on_backpressure(self):
        """net_codec=auto: raw until full_waits grows, compressed after,
        raw again once the backpressure stays quiet."""
        w = NetWriter({"host": "127.0.0.1", "port": 1, "token": 1,
                       "wid": 0, "attempt": 0, "codec": "auto",
                       "coalesce": 1 << 20})
        assert w._effective_codec() == CODEC_OFF
        w.full_waits += 3                  # kernel buffer pushed back
        w._auto_update()
        assert w._effective_codec() == CODEC_ZLIB
        from ape_x_dqn_tpu.runtime.net import _AUTO_OFF_FLUSHES

        for _ in range(_AUTO_OFF_FLUSHES):  # a long quiet spell
            w._auto_update()
        assert w._effective_codec() == CODEC_OFF
        w.close()

    def test_max_wait_flush_on_next_write(self):
        tr = NetTransport()
        try:
            ch = tr.make_channel(0, 0)
            w = self._writer(tr, coalesce=64 << 20, coalesce_wait_ms=1.0)
            assert w.write([b"one"], timeout=5)
            time.sleep(0.05)               # max-wait elapses
            assert w.write([b"two"], timeout=5)   # triggers the flush
            got = []
            deadline = time.monotonic() + 5
            while len(got) < 2 and time.monotonic() < deadline:
                tr.pump()
                rec = ch.read_next()
                if rec is not None:
                    got.append(rec)
            assert got == [b"one", b"two"]
            assert tr.stats()["coalesced_frames_in"] == 1
            w.close()
        finally:
            tr.close()


class TestPoolWireEfficiency:
    """Pool-level: the config-driven wire layers feed replay ingest the
    IDENTICAL decoded chunks, and the `net` section reports the ratio."""

    def test_pool_ingest_bit_exact_under_codec_and_coalesce(self):
        from ape_x_dqn_tpu.config import ApexConfig
        from ape_x_dqn_tpu.runtime.process_actors import ProcessActorPool
        from ape_x_dqn_tpu.runtime.transport import connect_channel

        cfg = ApexConfig()
        cfg.network = "mlp"
        cfg.env.name = "chain:6"
        cfg.actor.mode = "process"
        cfg.actor.transport = "tcp"
        cfg.actor.net_codec = "zlib"
        cfg.actor.net_coalesce_bytes = 1 << 20
        cfg.actor.num_workers = 1
        cfg.actor.num_actors = 2
        cfg.validate()
        pool = ProcessActorPool(cfg, num_workers=1, ring_bytes=1 << 16)
        try:
            pool._queues[0] = pool._ctx.Queue(maxsize=4)
            pool._rings[0] = pool._transport.make_channel(0, 0)
            spec = pool._transport.endpoint(pool._rings[0], 0, 0)
            assert spec["codec"] == "zlib" and spec["coalesce"] == 1 << 20
            w = connect_channel(spec)
            rng = np.random.default_rng(11)
            frames = rng.integers(0, 255, (7, 8, 8, 1), dtype=np.uint8)
            arrays = {"prio": rng.random(4).astype(np.float32),
                      "obs": frames[:4],
                      "action": np.arange(4, dtype=np.int32),
                      "reward": rng.normal(size=4).astype(np.float32),
                      "discount": np.full(4, 0.97, np.float32),
                      "next_obs": frames[3:]}
            for seq in range(3):
                assert w.write(
                    encode_chunk_parts(XP, 20 + seq, 4, arrays), timeout=5
                )
            assert w.flush(timeout=5)
            items = []
            deadline = time.monotonic() + 5
            while len(items) < 3 and time.monotonic() < deadline:
                items.extend(pool.poll(max_items=8))
                time.sleep(0.01)
            assert len(items) == 3
            for prio, trans in items:
                np.testing.assert_array_equal(prio, arrays["prio"])
                np.testing.assert_array_equal(trans.obs, arrays["obs"])
                np.testing.assert_array_equal(trans.next_obs,
                                              arrays["next_obs"])
            net = pool.net_stats()
            assert net["torn_frames"] == 0
            assert net["frames_in"] == 3
            assert net["coalesced_frames_in"] >= 1
            assert net["wire_over_logical"] < 1.0
            w.close()
        finally:
            pool.stop(join_timeout=1.0)


class TestClockSkewClamp:
    def test_future_t_act_clamped_and_counted(self):
        """A remote host's monotonic clock running ahead stamps t_act in
        our future; the span is clamped at zero age and counted, never
        emitted negative."""
        from ape_x_dqn_tpu.obs.lineage import LineageTracker

        events = []
        lt = LineageTracker(
            64, emit=lambda name, **kw: events.append((name, kw))
        )
        skewed = time.monotonic() + 3600.0   # one hour ahead
        lt.on_ingest(np.arange(4), t_act=skewed, trace_id=77, wid=0)
        assert lt.clock_skew_clamped == 1
        lt.on_sample(np.arange(4))
        lt.on_trained(np.arange(4))
        assert lt.completed_count == 1
        (_, span), = events
        assert span["act_to_ingest_ms"] >= 0.0
        assert span["act_to_trained_ms"] >= 0.0
        assert span["t_act"] <= span["t_ingest"]
        assert lt.summary()["clock_skew_clamped"] == 1

    def test_sane_t_act_not_clamped(self):
        from ape_x_dqn_tpu.obs.lineage import LineageTracker

        lt = LineageTracker(64)
        lt.on_ingest(np.arange(4), t_act=time.monotonic() - 0.5,
                     trace_id=5, wid=0)
        assert lt.clock_skew_clamped == 0
