"""Async runtime tests: param store, prefetch infeed, full pipeline,
actor-crash supervision (SURVEY §4 level 2 + §5 failure detection)."""

import io
import json
import threading
import time

import numpy as np
import pytest

from ape_x_dqn_tpu.config import ApexConfig
from ape_x_dqn_tpu.runtime import AsyncPipeline, ParamStore, PrefetchQueue
from ape_x_dqn_tpu.utils.metrics import MetricLogger, RateCounter


class TestParamStore:
    def test_publish_get_versioning(self):
        store = ParamStore()
        assert store.get(-1) is None
        store.publish({"w": np.ones(3)})
        got = store.get(-1)
        assert got is not None
        params, v = got
        assert v == 1 and np.allclose(params["w"], 1)
        assert store.get(1) is None  # up to date
        store.publish({"w": np.zeros(3)})
        params, v = store.get(1)
        assert v == 2

    def test_get_blocking_times_out(self):
        store = ParamStore()
        with pytest.raises(TimeoutError):
            store.get_blocking(timeout=0.1)

    def test_get_blocking_sees_late_publish(self):
        store = ParamStore()

        def pub():
            time.sleep(0.05)
            store.publish({"w": np.ones(1)})

        threading.Thread(target=pub).start()
        params, v = store.get_blocking(timeout=2.0)
        assert v == 1


class TestPrefetchQueue:
    def test_prefetches_and_orders(self):
        produced = []

        def sample():
            produced.append(len(produced))
            return produced[-1]

        with PrefetchQueue(sample, place_fn=lambda x: x * 10, depth=2) as q:
            got = [q.get() for _ in range(5)]
        assert got == [0, 10, 20, 30, 40]

    def test_feeder_error_surfaces(self):
        def sample():
            raise RuntimeError("replay exploded")

        with PrefetchQueue(sample, place_fn=lambda x: x) as q:
            with pytest.raises(RuntimeError, match="infeed feeder failed"):
                q.get(timeout=2.0)

    def test_bounded_depth(self):
        calls = []

        def sample():
            calls.append(1)
            return 1

        with PrefetchQueue(sample, place_fn=lambda x: x, depth=2) as q:
            time.sleep(0.3)
            # depth 2 + at most one in-flight sample
            assert len(calls) <= 4

    def test_timeout_is_wall_clock_from_call_entry(self):
        """A sub-200 ms timeout must fire on time: the old get() only
        started its deadline after the first queue.Empty and waited a flat
        min(0.2, timeout) per retry, so timeouts overshot by up to a whole
        retry period (and get(10.0) by ~0.2 s systematically)."""

        def sample():
            time.sleep(30.0)  # feeder never delivers
            return 1

        with PrefetchQueue(sample, place_fn=lambda x: x) as q:
            t0 = time.monotonic()
            with pytest.raises(TimeoutError, match="starved"):
                q.get(timeout=0.15)
            elapsed = time.monotonic() - t0
        assert 0.15 <= elapsed < 0.6, elapsed


class TestMetrics:
    def test_rate_counter(self):
        rc = RateCounter(window_s=10)
        for _ in range(5):
            rc.add(2)
        assert rc.total == 10
        assert rc.rate() > 0

    def test_logger_jsonl(self):
        buf = io.StringIO()
        m = MetricLogger(stream=buf)
        m.log("loss", 1.0)
        m.log("loss", 3.0)
        rec = m.emit(step=7)
        line = buf.getvalue().strip()
        parsed = json.loads(line)
        assert parsed["loss"] == 2.0 and parsed["loss/n"] == 2
        assert parsed["step"] == 7
        assert rec["loss/max"] == 3.0


def pipeline_config() -> ApexConfig:
    cfg = ApexConfig()
    cfg.env.name = "chain:6"
    cfg.network = "mlp"
    cfg.actor.num_actors = 4
    cfg.actor.flush_every = 8
    cfg.actor.sync_every = 64
    cfg.actor.gamma = 0.9
    cfg.learner.min_replay_mem_size = 256
    cfg.learner.replay_sample_size = 32
    cfg.learner.total_steps = 10_000
    cfg.learner.publish_every = 10
    cfg.learner.optimizer = "adam"
    cfg.learner.learning_rate = 1e-3
    cfg.replay.capacity = 10_000
    return cfg.validate()


class TestAsyncPipeline:
    def test_runs_to_target_and_joins(self):
        buf = io.StringIO()
        pipe = AsyncPipeline(
            pipeline_config(), logger=MetricLogger(stream=buf), log_every=50
        )
        final = pipe.run(learner_steps=150, warmup_timeout=120.0)
        assert pipe.learner_step == 150
        assert final["replay_size"] >= 256
        assert final["actor_steps"] > 0
        assert final["param_version"] >= 1
        # JSONL stream parses, includes periodic emits.
        lines = [json.loads(l) for l in buf.getvalue().splitlines()]
        assert len(lines) >= 2
        assert pipe.worker.restarts == 0
        # Learner state advanced and actors saw published params.
        assert int(pipe.comps.state.step) == 150
        # Per-stage timers exported (SURVEY §5 tracing subsystem).
        assert "sample+place" in final["stage_us"]
        assert "step_dispatch" in final["stage_us"]
        assert final["stage_us"]["step_dispatch"] > 0

    def test_priorities_written_back(self):
        pipe = AsyncPipeline(pipeline_config(), logger=MetricLogger(stream=io.StringIO()))
        before = pipe.comps.replay._tree.total
        pipe.run(learner_steps=60, warmup_timeout=120.0)
        after = pipe.comps.replay._tree.total
        # Learner TD priorities replace actor initial priorities; totals move.
        assert after != pytest.approx(before)

    def test_actor_crash_respawns(self):
        cfg = pipeline_config()
        crashed = {"n": 0}

        import ape_x_dqn_tpu.envs as envs_mod
        from ape_x_dqn_tpu.envs import ChainMDP

        class CrashingChain(ChainMDP):
            def step(self, action):
                # Crash the whole fleet once, early.
                if crashed["n"] == 0 and self._t > 10:
                    crashed["n"] += 1
                    raise RuntimeError("injected env fault")
                return super().step(action)

        pipe = AsyncPipeline(cfg, logger=MetricLogger(stream=io.StringIO()))
        # Swap one env constructor for the crashing variant.
        pipe.comps.env_fns[0] = lambda: CrashingChain(6, time_limit=20)
        pipe.run(learner_steps=60, warmup_timeout=120.0)
        assert crashed["n"] == 1
        assert pipe.worker.restarts == 1
        assert pipe.learner_step == 60

    def test_truncation_unbiased_value_async(self):
        """Async twin of test_truncation_unbiased_value_sync: the pipeline's
        threaded actor path must apply the same truncation bootstrap."""
        import jax
        import jax.numpy as jnp

        cfg = pipeline_config()
        cfg.env.name = "loop:10"
        cfg.actor.gamma = 0.9
        cfg.learner.loss = "squared"
        cfg.learner.learning_rate = 3e-3
        cfg.learner.q_target_sync_freq = 25
        cfg.learner.min_replay_mem_size = 200
        pipe = AsyncPipeline(cfg, logger=MetricLogger(stream=io.StringIO()))
        pipe.run(learner_steps=2000, warmup_timeout=120.0)
        q = np.asarray(
            pipe.comps.network.apply(
                pipe.comps.state.params,
                jnp.full((1, 4), 255, jnp.uint8),
            )[2]
        )
        assert q.max() > 8.5, f"Q biased toward truncation cutoff: {q}"
        assert q.max() < 12.0, f"Q diverged: {q}"

    def test_actor_permafail_raises(self):
        cfg = pipeline_config()

        from ape_x_dqn_tpu.envs import ChainMDP

        class AlwaysCrash(ChainMDP):
            def step(self, action):
                raise RuntimeError("permanent fault")

        pipe = AsyncPipeline(
            cfg, logger=MetricLogger(stream=io.StringIO()), max_actor_restarts=2
        )
        pipe.comps.env_fns = [lambda: AlwaysCrash(6)] * cfg.actor.num_actors
        with pytest.raises(RuntimeError):
            pipe.run(learner_steps=50, warmup_timeout=5.0)


class TestActorBudgetAccounting:
    def test_thread_fleet_lands_on_T_exactly(self):
        """actor.T bounds TOTAL env steps: with a quantum that doesn't
        divide T, the final collect must be clamped (round-3 verdict weak
        item 5 — unclamped fleets overshot by up to quantum-1 steps)."""
        from ape_x_dqn_tpu.runtime.async_pipeline import _ActorWorker
        from ape_x_dqn_tpu.runtime.components import build_components

        cfg = pipeline_config()
        cfg.actor.T = 53  # 53 % 8 != 0
        comps = build_components(cfg)
        store = ParamStore(comps.state.params)
        worker = _ActorWorker(
            comps, store, threading.Event(),
            MetricLogger(stream=io.StringIO()), RateCounter(), quantum=8,
        )
        worker.start()
        worker.join(timeout=120.0)
        assert worker.finished
        assert worker.fleet_steps == 53


def test_multihost_config_validation(monkeypatch):
    """Round-3 advisor (medium): multi-host runs must reject data_parallel=1
    (N silently-divergent models) and the fused HBM path (no multi-host
    checkpoint/replay story) at init."""
    import jax

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)

    cfg = pipeline_config()
    with pytest.raises(ValueError, match="data_parallel > 1"):
        AsyncPipeline(cfg, logger=MetricLogger(stream=io.StringIO()))

    cfg2 = pipeline_config()
    cfg2.learner.device_replay = True
    with pytest.raises(ValueError, match="single-process only"):
        AsyncPipeline(cfg2, logger=MetricLogger(stream=io.StringIO()))

    cfg3 = pipeline_config()
    cfg3.learner.data_parallel = 2
    cfg3.learner.replay_sample_size = 33
    cfg3.replay.capacity = 10_000
    with pytest.raises(ValueError, match="divi"):
        AsyncPipeline(cfg3, logger=MetricLogger(stream=io.StringIO()))


def test_metric_logger_tensorboard_sink(tmp_path):
    """Optional TensorBoard sink (SURVEY §5): scalar events land in the
    log dir; absence of torch degrades to a warning (gated import)."""
    import os

    pytest.importorskip("torch")

    logger = MetricLogger(stream=io.StringIO(),
                          tensorboard_dir=str(tmp_path / "tb"))
    logger.log("learner/loss", 0.5)
    logger.log("learner/loss", 0.7)
    logger.emit(step=10, steps_per_sec=123.0)
    logger.close()
    files = os.listdir(tmp_path / "tb")
    assert any(f.startswith("events.out.tfevents") for f in files), files


def test_trim_malloc_available_and_safe():
    """utils.memory.trim_malloc: on this glibc image it must actually run
    (the round-5 soak measured unbounded RSS growth without it); on any
    platform it must be a safe no-op at worst."""
    from ape_x_dqn_tpu.utils.memory import trim_malloc

    assert trim_malloc() is True   # glibc present in this image
    assert trim_malloc() is True   # idempotent / repeatable
