"""utils/metrics edge cases + the (seq, pid) record-stamping contract
(ISSUE 4 satellites: histogram/rate-counter corners, TransportStats
merge, deterministic multi-process JSONL ordering)."""

import io
import json
import math
import os

import numpy as np

from ape_x_dqn_tpu.utils.metrics import (
    LatencyHistogram,
    MetricLogger,
    RateCounter,
    TransportStats,
    emit_event,
)


class TestLatencyHistogramEdges:
    def test_empty_percentiles_nan_and_summary_count_zero(self):
        h = LatencyHistogram()
        assert math.isnan(h.percentile(50))
        assert math.isnan(h.percentile(99))
        assert h.summary() == {"count": 0}
        assert h.buckets() == {}

    def test_single_sample_all_percentiles_clamp_to_it(self):
        h = LatencyHistogram()
        h.record(0.0123)
        s = h.summary()
        assert s["count"] == 1
        # One sample: every percentile is that sample (clamped to max —
        # the bucket's upper edge must not overstate a lone observation).
        assert s["p50_ms"] == s["p99_ms"] == s["max_ms"] == 12.3
        assert abs(s["mean_ms"] - 12.3) < 1e-9

    def test_underflow_and_overflow_buckets(self):
        h = LatencyHistogram(min_s=1e-3, max_s=1.0)
        h.record(1e-9)     # below min_s — underflow bucket
        h.record(1e9)      # way past max_s — overflow bucket
        assert h.count == 2
        assert h.percentile(1) <= 1e-3
        assert "+Inf" in h.buckets()

    def test_merge_sums_counts_and_rejects_layout_mismatch(self):
        a, b = LatencyHistogram(), LatencyHistogram()
        for v in (0.001, 0.01):
            a.record(v)
        for v in (0.1, 1.0, 10.0):
            b.record(v)
        a.merge(b)
        assert a.count == 5
        assert a.summary()["max_ms"] == 10_000.0
        mismatched = LatencyHistogram(min_s=1e-3)
        try:
            a.merge(mismatched)
            raise AssertionError("layout mismatch must raise")
        except ValueError:
            pass


class TestRateCounterEdges:
    def test_empty_rate_is_zero(self):
        assert RateCounter().rate() == 0.0

    def test_clock_adjacent_zero_interval_is_finite_and_bounded(self):
        """An add() in the same tick as rate(): the old 1e-9 span floor
        reported count/1e-9 ≈ 1e9 events/s for a single event — absurd.
        The 1 ms floor bounds the transient to count/1e-3."""
        c = RateCounter(window_s=10.0)
        c.add(5)
        r = c.rate()
        assert math.isfinite(r)
        assert 0.0 < r <= 5 / 1e-3 + 1e-6

    def test_merge_interleaves_totals(self):
        a, b = RateCounter(window_s=60.0), RateCounter(window_s=60.0)
        a.add(2)
        b.add(3)
        a.merge(b)
        assert a.total == 5.0
        assert a.rate() > 0.0


class TestTransportStatsMerge:
    def test_merge_sums_counters_rates_and_latency(self):
        a, b = TransportStats(), TransportStats()
        a.record_chunk(1000, 0.01, 16)
        a.count_salvage(3, torn=True)
        b.record_chunk(2000, 0.02, 32)
        b.record_chunk(4000, 0.04, 64)
        b.count_salvage(1, torn=False)
        a.merge(b)
        s = a.summary()
        assert s["chunks"] == 3
        assert s["transitions"] == 112
        assert s["salvaged_records"] == 4
        assert s["torn_records"] == 1
        assert a.latency.count == 3
        assert a.bytes == 7000
        # Window rates interleave — the merged rate sees all three chunks.
        assert a.chunk_rate.total == 3.0


class TestRecordStamping:
    def test_emit_event_stamps_seq_and_pid(self):
        buf = io.StringIO()
        r1 = emit_event("x", stream=buf, a=1)
        r2 = emit_event("y", stream=buf)
        assert r1["pid"] == r2["pid"] == os.getpid()
        assert r2["seq"] > r1["seq"] > 0
        lines = [json.loads(line) for line in buf.getvalue().splitlines()]
        assert [line["seq"] for line in lines] == [r1["seq"], r2["seq"]]

    def test_logger_emit_and_event_share_one_monotone_sequence(self):
        buf = io.StringIO()
        log = MetricLogger(stream=buf)
        log.log("v", 1.0)
        a = log.emit(step=1)
        b = log.event("thing", detail=2)
        c = log.emit(step=2)
        seqs = [a["seq"], b["seq"], c["seq"]]
        assert seqs == sorted(seqs) and len(set(seqs)) == 3
        assert all(r["pid"] == os.getpid() for r in (a, b, c))

    def test_existing_stamps_win(self):
        """Re-emitting a merged stream must not restamp (the merge key
        would be destroyed)."""
        r = emit_event("x", stream=io.StringIO(), seq=777, pid=42)
        assert r["seq"] == 777 and r["pid"] == 42

    def test_numpy_values_do_not_break_stamping(self):
        buf = io.StringIO()
        log = MetricLogger(stream=buf)
        log.log("v", float(np.float32(2.5)))
        rec = log.emit()
        assert "seq" in rec and "pid" in rec
