"""utils/metrics edge cases + the (seq, pid) record-stamping contract
(ISSUE 4 satellites: histogram/rate-counter corners, TransportStats
merge, deterministic multi-process JSONL ordering)."""

import io
import json
import math
import os

import numpy as np

from ape_x_dqn_tpu.utils.metrics import (
    LatencyHistogram,
    MetricLogger,
    RateCounter,
    TransportStats,
    bucket_percentile,
    emit_event,
    merge_bucket_dicts,
    merge_counter_maps,
)


class TestLatencyHistogramEdges:
    def test_empty_percentiles_nan_and_summary_count_zero(self):
        h = LatencyHistogram()
        assert math.isnan(h.percentile(50))
        assert math.isnan(h.percentile(99))
        assert h.summary() == {"count": 0}
        assert h.buckets() == {}

    def test_single_sample_all_percentiles_clamp_to_it(self):
        h = LatencyHistogram()
        h.record(0.0123)
        s = h.summary()
        assert s["count"] == 1
        # One sample: every percentile is that sample (clamped to max —
        # the bucket's upper edge must not overstate a lone observation).
        assert s["p50_ms"] == s["p99_ms"] == s["max_ms"] == 12.3
        assert abs(s["mean_ms"] - 12.3) < 1e-9

    def test_underflow_and_overflow_buckets(self):
        h = LatencyHistogram(min_s=1e-3, max_s=1.0)
        h.record(1e-9)     # below min_s — underflow bucket
        h.record(1e9)      # way past max_s — overflow bucket
        assert h.count == 2
        assert h.percentile(1) <= 1e-3
        assert "+Inf" in h.buckets()

    def test_merge_sums_counts_and_rejects_layout_mismatch(self):
        a, b = LatencyHistogram(), LatencyHistogram()
        for v in (0.001, 0.01):
            a.record(v)
        for v in (0.1, 1.0, 10.0):
            b.record(v)
        a.merge(b)
        assert a.count == 5
        assert a.summary()["max_ms"] == 10_000.0
        mismatched = LatencyHistogram(min_s=1e-3)
        try:
            a.merge(mismatched)
            raise AssertionError("layout mismatch must raise")
        except ValueError:
            pass


class TestRateCounterEdges:
    def test_empty_rate_is_zero(self):
        assert RateCounter().rate() == 0.0

    def test_clock_adjacent_zero_interval_is_finite_and_bounded(self):
        """An add() in the same tick as rate(): the old 1e-9 span floor
        reported count/1e-9 ≈ 1e9 events/s for a single event — absurd.
        The 1 ms floor bounds the transient to count/1e-3."""
        c = RateCounter(window_s=10.0)
        c.add(5)
        r = c.rate()
        assert math.isfinite(r)
        assert 0.0 < r <= 5 / 1e-3 + 1e-6

    def test_merge_interleaves_totals(self):
        a, b = RateCounter(window_s=60.0), RateCounter(window_s=60.0)
        a.add(2)
        b.add(3)
        a.merge(b)
        assert a.total == 5.0
        assert a.rate() > 0.0


class TestTransportStatsMerge:
    def test_merge_sums_counters_rates_and_latency(self):
        a, b = TransportStats(), TransportStats()
        a.record_chunk(1000, 0.01, 16)
        a.count_salvage(3, torn=True)
        b.record_chunk(2000, 0.02, 32)
        b.record_chunk(4000, 0.04, 64)
        b.count_salvage(1, torn=False)
        a.merge(b)
        s = a.summary()
        assert s["chunks"] == 3
        assert s["transitions"] == 112
        assert s["salvaged_records"] == 4
        assert s["torn_records"] == 1
        assert a.latency.count == 3
        assert a.bytes == 7000
        # Window rates interleave — the merged rate sees all three chunks.
        assert a.chunk_rate.total == 3.0


class TestSerializedMerges:
    """The fleet rollup's merge arithmetic (ISSUE 14 satellite): the
    SERIALIZED twins of the object-level merge() — bucket dicts, counter
    maps, shipped histogram states — pinned associative + commutative,
    because an aggregator restart / re-scrape must not change the math."""

    def _hists(self):
        hs = []
        for vals in ((0.001, 0.01), (0.1, 1.0), (5.0, 0.002, 0.3)):
            h = LatencyHistogram()
            for v in vals:
                h.record(v)
            hs.append(h)
        return hs

    def test_bucket_merge_matches_object_merge(self):
        a, b, _ = self._hists()
        merged = merge_bucket_dicts(a.buckets(), b.buckets())
        a.merge(b)
        assert merged == a.buckets()
        # Percentiles off the merged buckets = the object's bucket edges
        # (clamp-to-max aside, which serialization cannot carry).
        assert bucket_percentile(merged, 50) <= a.percentile(95) * 10

    def test_bucket_merge_associative_commutative(self):
        a, b, c = (h.buckets() for h in self._hists())
        ab_c = merge_bucket_dicts(merge_bucket_dicts(a, b), c)
        a_bc = merge_bucket_dicts(a, merge_bucket_dicts(b, c))
        assert ab_c == a_bc
        assert merge_bucket_dicts(a, b) == merge_bucket_dicts(b, a)

    def test_bucket_percentile_empty_and_overflow(self):
        assert math.isnan(bucket_percentile({}, 50))
        assert bucket_percentile({"+Inf": 3}, 99) == float("inf")

    def test_state_dict_merge_matches_object_merge(self):
        a, b, _ = self._hists()
        ref = LatencyHistogram()
        ref.merge(a)
        ref.merge(b)
        target = LatencyHistogram()
        assert target.merge_state(a.state_dict())
        assert target.merge_state(b.state_dict())
        assert target.state_dict() == ref.state_dict()
        # Layout mismatch: refused, never silently misaligned.
        other = LatencyHistogram(min_s=1e-3)
        assert not other.merge_state(a.state_dict())

    def test_counter_map_merge_associative_commutative(self):
        a = {"requests": 3, "ops": {"add": 1}, "port": "x"}
        b = {"requests": 5, "ops": {"add": 2, "sample": 7}}
        c = {"requests": 1, "torn": 4}
        ab_c = merge_counter_maps(merge_counter_maps(a, b), c)
        a_bc = merge_counter_maps(a, merge_counter_maps(b, c))
        assert ab_c == a_bc
        assert merge_counter_maps(a, b) == merge_counter_maps(b, a)
        assert ab_c["requests"] == 9
        assert ab_c["ops"] == {"add": 3, "sample": 7}
        assert ab_c["port"] == "x"       # non-numeric rides through

    def test_health_merge_freshest_beat_wins(self):
        import time as _time

        from ape_x_dqn_tpu.obs.registry import Health

        a, b = Health(stale_after_s=100.0), Health(stale_after_s=100.0)
        a.beat("learner")
        _time.sleep(0.01)
        b.beat("learner")
        b.beat("ingest")
        fresh_age = b.status()["components"]["learner"]["age_s"]
        a.merge(b)
        st = a.status()
        assert set(st["components"]) == {"learner", "ingest"}
        # The fresher beat won (merge order must not resurrect staleness).
        assert st["components"]["learner"]["age_s"] <= fresh_age + 0.05
        # Commutative: merging the other way yields the same component
        # ages (modulo clock advance between the two status reads).
        c = Health(stale_after_s=100.0)
        c.beat("ingest")
        c.merge(a)
        assert set(c.status()["components"]) == {"learner", "ingest"}

    def test_registry_instrument_merges(self):
        from ape_x_dqn_tpu.obs.registry import Counter, Gauge, Histogram

        c1, c2 = Counter(), Counter()
        c1.inc(3)
        c2.inc(4)
        c1.merge(c2)
        assert c1.value == 7
        g1, g2 = Gauge(), Gauge()
        g1.set(0.4)
        g2.set(0.9)
        g1.merge(g2)
        assert g1.value == 0.9           # conservative max
        h1, h2 = Histogram(), Histogram()
        h1.observe(0.01)
        h2.observe(0.1)
        h1.merge(h2)
        assert h1.count == 2


class TestRecordStamping:
    def test_emit_event_stamps_seq_and_pid(self):
        buf = io.StringIO()
        r1 = emit_event("x", stream=buf, a=1)
        r2 = emit_event("y", stream=buf)
        assert r1["pid"] == r2["pid"] == os.getpid()
        assert r2["seq"] > r1["seq"] > 0
        lines = [json.loads(line) for line in buf.getvalue().splitlines()]
        assert [line["seq"] for line in lines] == [r1["seq"], r2["seq"]]

    def test_logger_emit_and_event_share_one_monotone_sequence(self):
        buf = io.StringIO()
        log = MetricLogger(stream=buf)
        log.log("v", 1.0)
        a = log.emit(step=1)
        b = log.event("thing", detail=2)
        c = log.emit(step=2)
        seqs = [a["seq"], b["seq"], c["seq"]]
        assert seqs == sorted(seqs) and len(set(seqs)) == 3
        assert all(r["pid"] == os.getpid() for r in (a, b, c))

    def test_existing_stamps_win(self):
        """Re-emitting a merged stream must not restamp (the merge key
        would be destroyed)."""
        r = emit_event("x", stream=io.StringIO(), seq=777, pid=42)
        assert r["seq"] == 777 and r["pid"] == 42

    def test_numpy_values_do_not_break_stamping(self):
        buf = io.StringIO()
        log = MetricLogger(stream=buf)
        log.log("v", float(np.float32(2.5)))
        rec = log.emit()
        assert "seq" in rec and "pid" in rec
