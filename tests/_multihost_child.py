"""Child program for tests/test_multihost.py — one SPMD participant.

Run as: python tests/_multihost_child.py <process_id> <num_processes> <port>
Must be a standalone script (not under pytest): jax.distributed must
initialize before the backend exists, which a fresh process guarantees.
"""

import os
import sys


def main() -> int:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    os.environ["XLA_FLAGS"] = " ".join(
        [f for f in flags.split()
         if "force_host_platform_device_count" not in f]
        + ["--xla_force_host_platform_device_count=4"]
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    pid, n, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    mode = sys.argv[4] if len(sys.argv) > 4 else "step"
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from ape_x_dqn_tpu.parallel.multihost import (
        host_value,
        initialize_multihost,
        local_shard,
    )

    initialize_multihost(f"127.0.0.1:{port}", num_processes=n, process_id=pid)
    if mode == "pipeline":
        return pipeline_mode(pid, n)

    import jax.numpy as jnp
    import numpy as np

    from ape_x_dqn_tpu.learner.train_step import init_train_state, make_optimizer
    from ape_x_dqn_tpu.models.dueling import DuelingMLP
    from ape_x_dqn_tpu.parallel import build_sharded_train_step, make_mesh, place_batch
    from ape_x_dqn_tpu.types import NStepTransition, PrioritizedBatch

    assert len(jax.devices()) == 4 * n, jax.devices()
    net = DuelingMLP(num_actions=3, hidden_sizes=(32,))
    opt = make_optimizer("adam", learning_rate=1e-3)
    state = init_train_state(net, opt, jax.random.PRNGKey(0), jnp.zeros((1, 6)))
    mesh = make_mesh()  # the GLOBAL mesh: every process's devices
    B = 16
    r = np.random.default_rng(0)  # same stream in every process (SPMD)
    t = NStepTransition(
        obs=r.normal(size=(B, 6)).astype(np.float32),
        action=r.integers(0, 3, (B,)).astype(np.int32),
        reward=r.normal(size=(B,)).astype(np.float32),
        discount=np.full((B,), 0.97, np.float32),
        next_obs=r.normal(size=(B, 6)).astype(np.float32),
    )
    batch = PrioritizedBatch(
        transition=t,
        indices=np.arange(B, dtype=np.int32),
        is_weights=np.ones((B,), np.float32),
    )
    step_fn, sharded_state = build_sharded_train_step(
        net, opt, mesh, state, batch, target_sync_freq=100
    )
    gb = place_batch(batch, mesh)
    losses = []
    for _ in range(3):
        sharded_state, metrics = step_fn(sharded_state, gb)
        losses.append(float(host_value(metrics.loss)))
    mine = local_shard(metrics.priorities)
    # Each process owns B / n rows of the data-sharded priorities.
    assert mine.shape == (B // n,), mine.shape
    assert np.all(mine > 0)
    assert losses[2] < losses[0], losses
    print(f"RESULT {pid} {losses[2]:.8f} {int(host_value(sharded_state.step))}",
          flush=True)
    return 0


def pipeline_mode(pid: int, n: int) -> int:
    """The FULL async runtime per process — actors feeding a local replay,
    sampled local batches assembled into the global data-sharded batch,
    the all-reduced train step, per-host priority writeback — i.e. the
    multi-host Ape-X layout end to end on the CPU stand-in for a pod."""
    import jax
    import numpy as np

    from ape_x_dqn_tpu.config import ApexConfig
    from ape_x_dqn_tpu.parallel.multihost import host_value
    from ape_x_dqn_tpu.runtime.async_pipeline import AsyncPipeline

    cfg = ApexConfig()
    cfg.network = "mlp"
    cfg.env.name = "chain:6"
    cfg.actor.num_actors = 4
    cfg.actor.T = 1_000_000
    cfg.actor.flush_every = 8
    cfg.actor.sync_every = 16
    cfg.learner.data_parallel = len(jax.devices())   # the GLOBAL mesh
    cfg.learner.replay_sample_size = 32
    cfg.learner.min_replay_mem_size = 128
    cfg.learner.optimizer = "adam"
    cfg.replay.capacity = 4096
    # cfg.seed IDENTICAL on every host: replicated param placement asserts
    # cross-process equality.  Per-host exploration comes from the
    # pipeline's process-indexed fleet seed base and sampler salt.
    pipe = AsyncPipeline(cfg, log_every=100)
    assert pipe._n_proc == n, pipe._n_proc
    result = pipe.run(learner_steps=60, warmup_timeout=180.0)
    loss = result["learner/loss"]
    step = int(host_value(pipe.comps.state.step))
    # Params identical across hosts: all-reduce kept them in lockstep.
    p0 = host_value(jax.tree_util.tree_leaves(pipe.comps.state.params)[0])
    digest = float(np.sum(np.abs(p0)))
    print(f"RESULT {pid} {loss:.8f} {step} {digest:.8f}", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
