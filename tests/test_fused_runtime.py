"""FusedDeviceLearner host driver + device-replay async-pipeline mode.

CPU backend (conftest's 8 virtual devices); the same code paths run on the
real chip via bench.py and the `learner.device_replay=true` CLI config.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ape_x_dqn_tpu.config import ApexConfig
from ape_x_dqn_tpu.learner.train_step import init_train_state, make_optimizer
from ape_x_dqn_tpu.models.dueling import DuelingMLP
from ape_x_dqn_tpu.runtime.fused_learner import FusedDeviceLearner
from ape_x_dqn_tpu.types import NStepTransition


def np_chunk(m, obs_shape=(8,), seed=0):
    r = np.random.default_rng(seed)
    return NStepTransition(
        obs=r.integers(0, 255, (m, *obs_shape), dtype=np.uint8),
        action=r.integers(0, 3, (m,), dtype=np.int32),
        reward=r.normal(size=(m,)).astype(np.float32),
        discount=np.full((m,), 0.9, np.float32),
        next_obs=r.integers(0, 255, (m, *obs_shape), dtype=np.uint8),
    )


def make_learner(**kw):
    net = DuelingMLP(num_actions=3, hidden_sizes=(16,))
    opt = make_optimizer("rmsprop", learning_rate=1e-3, max_grad_norm=None)
    state = init_train_state(
        net, opt, jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.uint8)
    )
    defaults = dict(
        obs_shape=(8,), capacity=256, batch_size=16, steps_per_call=4,
        ingest_block=32, target_sync_freq=100,
    )
    defaults.update(kw)
    return FusedDeviceLearner(net, opt, state, **defaults)


class TestFusedDeviceLearner:
    def test_staging_blocks_and_partial_tail(self):
        fl = make_learner(ingest_block=32)
        fl.add_chunk(np.ones(20, np.float32), np_chunk(20, seed=1))
        fl.add_chunk(np.ones(20, np.float32), np_chunk(20, seed=2))
        assert fl.staged_rows == 40
        ingested = fl.ingest_staged()
        # One full 32-block goes to HBM; the 8-row tail stays staged.
        assert ingested == 32
        assert fl.size == 32
        assert fl.staged_rows == 8

    def test_drain_flushes_tail(self):
        fl = make_learner(ingest_block=32)
        fl.add_chunk(np.ones(20, np.float32), np_chunk(20))
        assert fl.ingest_staged(drain=True) == 20
        assert fl.size == 20
        assert fl.staged_rows == 0

    def test_train_advances_k_steps(self):
        fl = make_learner(steps_per_call=4)
        fl.add_chunk(np.ones(64, np.float32), np_chunk(64))
        fl.ingest_staged()
        metrics = fl.train(beta=0.4)
        assert fl.step == 4
        assert metrics.loss.shape == (4,)
        assert np.isfinite(np.asarray(metrics.loss)).all()
        metrics = fl.train(beta=0.4)
        assert fl.step == 8

    def test_chunk_order_preserved_through_staging(self):
        """Rows must land in the ring in arrival order (FIFO eviction
        depends on it): obs row i of the ring == row i of the stream."""
        fl = make_learner(ingest_block=16)
        c1, c2 = np_chunk(10, seed=3), np_chunk(10, seed=4)
        fl.add_chunk(np.ones(10, np.float32), c1)
        fl.add_chunk(np.ones(10, np.float32), c2)
        fl.ingest_staged(drain=True)
        ring_obs = np.asarray(fl._replay.obs)[:20]
        want = np.concatenate([c1.obs, c2.obs])
        np.testing.assert_array_equal(ring_obs, want)


class TestAsyncPipelineFusedMode:
    def test_end_to_end_device_replay_mode(self, tmp_path):
        from ape_x_dqn_tpu.runtime.async_pipeline import AsyncPipeline

        cfg = ApexConfig()
        cfg.env.name = "chain:6"
        cfg.network = "mlp"
        cfg.actor.num_actors = 4
        cfg.actor.T = 50_000
        cfg.actor.flush_every = 8
        cfg.learner.device_replay = True
        cfg.learner.steps_per_call = 8
        cfg.learner.min_replay_mem_size = 128
        cfg.learner.replay_sample_size = 16
        cfg.learner.max_grad_norm = None
        cfg.learner.second_moment_dtype = "bfloat16"
        cfg.learner.target_dtype = "bfloat16"
        cfg.learner.checkpoint_every = 32
        cfg.learner.checkpoint_dir = str(tmp_path / "ckpt")
        cfg.replay.capacity = 2048
        pipe = AsyncPipeline(cfg, log_every=32)
        out = pipe.run(learner_steps=64, warmup_timeout=120)
        assert out["step"] >= 64
        assert out["replay_size"] >= 128
        assert pipe.store.version > 0
        assert np.isfinite(out["learner/loss"])
        # Checkpoint written from the fused state.
        assert (tmp_path / "ckpt").exists()
