"""Outage-proof bench.py (VERDICT round-5 item 1): with the accelerator
backend forced unreachable, ``python bench.py`` must still exit 0 with ONE
parseable JSON line carrying the host-only sections (host_replay_2m,
host_dedup_2m, serving_qps) plus ``"platform_outage": true`` and the probe
evidence — the failure mode that ate BENCH_r05 can never eat a bench line
again."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_emits_host_only_json_during_outage():
    env = dict(os.environ)
    # Force unreachable: demand a TPU backend this image does not have (and
    # drop the plugin gate so sitecustomize cannot rescue it).  The probe
    # subprocess fails; in a real tunnel outage it hangs and the hard
    # timeout fires — either way the probe reports ok=False.
    env["JAX_PLATFORMS"] = "tpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    cmd = [
        sys.executable, "bench.py",
        "--probe-timeout", "60",
        "--host-replay-capacity", "8192",   # tiny: mechanism, not scale
        "--serving-clients", "4",
        "--serving-duration", "1.0",
        "--serving-network", "mlp",
        "--serving-max-batch", "8",
        "--xp-workers", "2",                # tiny: mechanism, not scale
        "--xp-seconds", "0.5",
        "--ckpt-capacity", "8192",          # tiny: mechanism, not scale
        "--ckpt-interval-rows", "4096",
        "--pipeline-overlap-steps", "1024",  # tiny: mechanism, not scale
        "--pipeline-overlap-sync-every", "256",
        "--replay-svc-iters", "30",          # tiny: mechanism, not scale
        "--replay-svc-capacity", "2048",
        "--replay-svc-rows", "1024",
        "--central-widths", "2",             # tiny: mechanism, not scale
        "--central-measure-s", "1.0",
        "--central-skip-kill",               # the smoke leg is gate 11's
    ]
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=560, env=env, cwd=REPO,
    )
    assert proc.returncode == 0, (proc.stdout[-500:], proc.stderr[-2000:])
    lines = [l for l in proc.stdout.strip().splitlines() if l.strip()]
    rec = json.loads(lines[-1])                 # ONE parseable line
    assert rec["platform_outage"] is True
    assert rec["value"] is None
    assert rec["vs_baseline"] is None
    assert rec["backend_probe"]["ok"] is False
    assert rec["backend_probe"]["error"]
    # Host-only sections survive the outage...
    for key in ("host_replay_2m", "host_dedup_2m", "serving_qps",
                "xp_transport", "checkpoint_stall", "pipeline_overlap",
                "replay_svc", "central_inference"):
        assert key in rec, f"missing host-only section {key}"
    ci = rec["central_inference"]
    assert "error" not in ci, ci
    assert all(p["env_steps_per_s"] > 0 for p in ci["points"])
    assert all(p["torn_replies"] == 0 for p in ci["points"])
    rs = rec["replay_svc"]
    assert "error" not in rs, rs
    assert rs["in_process"]["samples_per_s"] > 0
    assert rs["rpc_1shard"]["samples_per_s"] > 0
    po = rec["pipeline_overlap"]
    assert "error" not in po, po
    assert po["points"]["depth4"]["inflight_at_exit"] == 0
    assert rec["host_replay_2m"].get("sample_update_pairs_per_sec", 0) > 0
    cs = rec["checkpoint_stall"]
    if "skipped" not in cs:  # native core present on this machine
        assert "error" not in cs, cs
        assert cs["stall_reduction_x"] > 1.0
    # ...including the serving bench, which pins its child to CPU.
    sq = rec["serving_qps"]
    assert "error" not in sq, sq
    assert sq["batched_qps"] > 0
    assert sq["reloads"] >= 1
    # No on-chip section was attempted against the dead backend.
    for key in ("fused", "dedup_fused", "samplers_2m", "pipeline"):
        assert key not in rec
