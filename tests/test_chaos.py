"""Chaos injection framework (obs/chaos.py): the injectors and the seeded
schedule.

The contract under test: every injector produces a fault the recovery
machinery DETECTS (torn tails counted and never delivered; corrupted
chunks typed as ChunkCorrupt with path + generation), and the monkey's
schedule is a pure function of (config, seed) — a failing chaos run
reproduces.
"""

import random

import numpy as np
import pytest

from ape_x_dqn_tpu.config import ChaosConfig
from ape_x_dqn_tpu.obs.chaos import (
    ChaosMonkey,
    ShmFiller,
    SlowEnv,
    corrupt_chunk,
    inject_torn_record,
    pick_chunk,
)


class TestTornRecordInjection:
    def _ring(self, capacity=1 << 16):
        from ape_x_dqn_tpu.runtime.shm_ring import ShmRing

        return ShmRing(capacity)

    def test_committed_records_survive_torn_tail_never_delivered(self):
        ring = self._ring()
        try:
            payloads = [bytes([i]) * 100 for i in range(3)]
            for p in payloads:
                assert ring.try_write([p])
            rec = inject_torn_record(ring, rng=random.Random(1))
            assert rec["fault"] == "torn_record"
            # Every committed record drains intact; the torn tail is never
            # delivered, and salvage accounting sees it.
            assert ring.drain() == payloads
            assert ring.read_next() is None
            assert ring.torn_tail()
        finally:
            ring.close()
            ring.unlink()

    def test_writer_can_resume_is_not_required_ring_is_retired(self):
        # The production discipline retires a torn ring (fresh ring per
        # incarnation); this only pins that the reader never misreads the
        # garbage as data even after more scans.
        ring = self._ring()
        try:
            assert ring.try_write([b"x" * 64])
            inject_torn_record(ring, rng=random.Random(2))
            assert len(ring.drain()) == 1
            for _ in range(3):
                assert ring.read_next() is None
        finally:
            ring.close()
            ring.unlink()


class TestCorruptChunk:
    def _write(self, tmp_path, name="chunk_3_1.ckpt"):
        from ape_x_dqn_tpu.utils.checkpoint_inc import write_chunk

        path = str(tmp_path / name)
        write_chunk(path, {"a": np.arange(64, dtype=np.int64),
                           "b": np.ones((8, 8), np.float32)})
        return path

    @pytest.mark.parametrize("mode", ["bitflip", "truncate", "zero"])
    def test_all_modes_surface_as_typed_chunk_corrupt(self, tmp_path, mode):
        from ape_x_dqn_tpu.utils.checkpoint_inc import ChunkCorrupt, read_chunk

        path = self._write(tmp_path)
        rec = corrupt_chunk(path, mode, rng=random.Random(5))
        assert rec["mode"] == mode
        with pytest.raises(ChunkCorrupt) as ei:
            read_chunk(path)
        # The typed error carries the forensic fields (satellite 2).
        assert ei.value.path == path
        assert ei.value.generation == 3
        assert ei.value.index == 1

    def test_unknown_mode_rejected(self, tmp_path):
        path = self._write(tmp_path)
        with pytest.raises(ValueError, match="unknown corruption mode"):
            corrupt_chunk(path, "melt")

    def test_pick_chunk_respects_manifest_and_preference(self, tmp_path):
        import json

        inc = tmp_path / "replay_inc"
        inc.mkdir()
        for name in ("chunk_0_0.ckpt", "chunk_0_1.ckpt"):
            self._write(inc, name)
        assert pick_chunk(str(inc)) is None  # no manifest, no pick
        (inc / "MANIFEST.json").write_text(json.dumps(
            {"chunks": ["chunk_0_0.ckpt", "chunk_0_1.ckpt"]}
        ))
        base = pick_chunk(str(inc), prefer="base")
        delta = pick_chunk(str(inc), prefer="delta")
        assert base.endswith("chunk_0_0.ckpt")
        assert delta.endswith("chunk_0_1.ckpt")


class TestSlowEnv:
    class _Env:
        observation_shape = (4,)
        num_actions = 2

        def reset(self):
            return np.zeros(4, np.uint8)

        def step(self, a):
            return np.zeros(4, np.uint8), 1.0, False, {}

    def test_latency_injected_semantics_preserved(self):
        import time

        env = SlowEnv(self._Env(), latency_s=0.01, seed=3)
        assert env.observation_shape == (4,)  # delegation
        assert env.num_actions == 2
        env.reset()
        t0 = time.monotonic()
        for _ in range(5):
            obs, r, done, info = env.step(0)
        elapsed = time.monotonic() - t0
        assert r == 1.0 and not done
        assert elapsed >= 5 * 0.01 * 0.5  # at least the jitter floor


class TestShmFiller:
    def test_fill_and_release(self):
        f = ShmFiller()
        rec = f.fill(1 << 20)
        assert rec["fault"] == "shm_fill"
        f.release()
        f.release()  # idempotent


class TestSchedule:
    def _cfg(self, **over):
        base = dict(enabled=True, seed=13, kill_interval_s=2.0,
                    torn_record_interval_s=5.0, sigstop_interval_s=0.0)
        base.update(over)
        return ChaosConfig(**base)

    def test_same_seed_same_schedule(self):
        a = ChaosMonkey(self._cfg(), horizon_s=60.0)
        b = ChaosMonkey(self._cfg(), horizon_s=60.0)
        assert a.schedule == b.schedule
        assert a.schedule, "enabled kinds must schedule events"
        kinds = {k for _, k in a.schedule}
        assert kinds == {"kill", "torn_record"}
        # Sorted timeline, events respect the mean-interval envelope.
        times = [t for t, _ in a.schedule]
        assert times == sorted(times)

    def test_different_seed_different_schedule(self):
        a = ChaosMonkey(self._cfg(), horizon_s=60.0)
        b = ChaosMonkey(self._cfg(seed=14), horizon_s=60.0)
        assert a.schedule != b.schedule

    def test_disabled_kinds_schedule_nothing(self):
        m = ChaosMonkey(self._cfg(kill_interval_s=0.0,
                                  torn_record_interval_s=0.0),
                        horizon_s=60.0)
        assert m.schedule == []

    def test_counters_and_provider_on_registry(self):
        from ape_x_dqn_tpu.obs.registry import MetricsRegistry

        reg = MetricsRegistry()
        m = ChaosMonkey(self._cfg(), registry=reg, horizon_s=10.0)
        # No pool attached: a kill is executed as a recorded skip, still
        # counted — chaos accounting must never silently drop an event.
        m.execute("kill")
        snap = reg.snapshot()
        assert snap["chaos/kill"]["total"] == 1.0
        assert snap["chaos"]["executed"] == 1
        assert m.counts() == {"kill": 1}
