"""Wire-format tests: flat-numpy snapshot <-> bytes (utils/serialization)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ape_x_dqn_tpu.learner.train_step import init_train_state, make_optimizer
from ape_x_dqn_tpu.models.dueling import DuelingMLP
from ape_x_dqn_tpu.utils.serialization import (
    restore_like,
    tree_from_bytes,
    tree_to_bytes,
)


def assert_trees_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        assert str(np.asarray(x).dtype) == str(np.asarray(y).dtype)
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestRoundTrip:
    def test_flax_params_standalone(self):
        net = DuelingMLP(num_actions=3, hidden_sizes=(16, 8))
        params = net.init(jax.random.PRNGKey(0), jnp.zeros((1, 6)))
        data = tree_to_bytes(jax.device_get(params))
        out = tree_from_bytes(data)
        assert_trees_equal(params, out)
        # The restored dict is directly usable by the network.
        q1 = net.apply(params, jnp.ones((2, 6)))[2]
        q2 = net.apply(out, jnp.ones((2, 6)))[2]
        np.testing.assert_allclose(np.asarray(q1), np.asarray(q2))

    def test_single_array(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        out = tree_from_bytes(tree_to_bytes(x))
        np.testing.assert_array_equal(out, x)

    def test_nested_lists_and_dicts(self):
        tree = {"a": [np.ones(3), {"b": np.zeros((2, 2), np.int32)}],
                "c": np.full(1, 7, np.uint8)}
        out = tree_from_bytes(tree_to_bytes(tree))
        assert_trees_equal(tree, out)

    def test_bfloat16_leaves(self):
        x = {"w": jnp.asarray([1.5, -2.25, 3.0], jnp.bfloat16)}
        out = tree_from_bytes(tree_to_bytes(jax.device_get(x)))
        assert out["w"].dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(out["w"], np.float32), np.asarray(x["w"], np.float32)
        )

    def test_train_state_restore_like(self):
        net = DuelingMLP(num_actions=3, hidden_sizes=(16,))
        opt = make_optimizer("rmsprop", second_moment_dtype=jnp.bfloat16,
                             max_grad_norm=None)
        state = init_train_state(net, opt, jax.random.PRNGKey(1),
                                 jnp.zeros((1, 6)), target_dtype=jnp.bfloat16)
        data = tree_to_bytes(jax.device_get(state))
        # A fresh template with different values restores to the original.
        template = init_train_state(net, opt, jax.random.PRNGKey(2),
                                    jnp.zeros((1, 6)), target_dtype=jnp.bfloat16)
        out = restore_like(jax.device_get(template), data)
        assert_trees_equal(state, out)


class TestErrors:
    def test_bad_magic(self):
        with pytest.raises(ValueError, match="bad magic"):
            tree_from_bytes(b"XXXX" + b"\0" * 32)

    def test_leaf_count_mismatch(self):
        data = tree_to_bytes({"a": np.ones(2)})
        with pytest.raises(ValueError, match="leaves"):
            restore_like({"a": np.ones(2), "b": np.ones(2)}, data)

    def test_shape_mismatch(self):
        data = tree_to_bytes({"a": np.ones(2)})
        with pytest.raises(ValueError, match="template"):
            restore_like({"a": np.ones(3)}, data)

    def test_path_mismatch(self):
        data = tree_to_bytes({"a": np.ones(2)})
        with pytest.raises(ValueError, match="path mismatch"):
            restore_like({"b": np.ones(2)}, data)

    def test_attr_paths_need_template(self):
        net = DuelingMLP(num_actions=3, hidden_sizes=(8,))
        opt = make_optimizer("adam")
        state = init_train_state(net, opt, jax.random.PRNGKey(0),
                                 jnp.zeros((1, 6)))
        data = tree_to_bytes(jax.device_get(state))
        with pytest.raises(ValueError, match="restore_like"):
            tree_from_bytes(data)
