"""Atari-stack hardening without ALE (round-3 verdict item 6): golden
preprocessing fixtures + the ALE-faithful fake emulator driving
EpisodicLife / FrameSkip / RewardClip's exact branch structure."""

import os

import numpy as np
import pytest

from ape_x_dqn_tpu.envs.atari import (
    EpisodicLife,
    FrameSkip,
    ObsPreprocess,
    RewardClip,
    wrap_dqn,
)
from ape_x_dqn_tpu.envs.fake_atari import FakeAtariEnv, make_fake_atari_env

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


class _OneFrame:
    observation_shape = (210, 160, 3)
    num_actions = 1

    def __init__(self, frame):
        self._frame = frame

    def reset(self, seed=None):
        return self._frame

    def step(self, action):
        raise NotImplementedError


class TestObsPreprocessGolden:
    def test_matches_committed_fixture(self):
        """Byte-exact against the committed golden outputs — catches silent
        drift in the cv2 luminance/resize path (regenerate via
        tests/fixtures/make_atari_golden.py only on INTENDED changes)."""
        with np.load(os.path.join(FIXTURES, "atari_golden.npz")) as z:
            i = 0
            while f"in_{i}" in z.files:
                got = ObsPreprocess(_OneFrame(z[f"in_{i}"]), 84, 84).reset()
                np.testing.assert_array_equal(got, z[f"out_{i}"])
                i += 1
        assert i >= 2

    def test_constant_frame_analytic_luminance(self):
        """Independent of cv2 versions: a constant-color frame maps to the
        ITU-R 601 luminance (0.299R+0.587G+0.114B) everywhere — resizing a
        constant image is the constant."""
        frame = np.zeros((210, 160, 3), np.uint8)
        frame[:] = (100, 150, 200)
        out = ObsPreprocess(_OneFrame(frame), 84, 84).reset()
        want = 0.299 * 100 + 0.587 * 150 + 0.114 * 200  # 140.75
        assert out.shape == (84, 84, 1)
        assert np.all(np.abs(out.astype(np.float64) - want) <= 1.0)


class TestFakeALEStack:
    def test_flicker_repaired_by_frameskip_maxpool(self):
        """The sprite renders only on even raw frames; FrameSkip's 2-frame
        max-pool must restore it in EVERY pooled observation."""
        raw = FakeAtariEnv(lives=99, steps_per_life=10_000)
        raw.reset()
        # Raw odd frames lack the sprite (value-255 pixels).
        odd = raw.step(0).obs   # t=1
        assert not (odd == 255).any()
        even = raw.step(0).obs  # t=2
        assert (even == 255).any()

        env = FrameSkip(FakeAtariEnv(lives=99, steps_per_life=10_000), 4)
        env.reset()
        for _ in range(10):
            r = env.step(0)
            assert (r.obs == 255).any(), "flicker leaked through max-pool"

    def test_episodic_life_terminates_per_life_without_reset(self):
        """A life loss must surface terminated=True to the learner while
        the underlying game continues (no emulator reset) — the corner
        pixel's step index proves frame continuity."""
        inner = FakeAtariEnv(lives=3, steps_per_life=5)
        env = EpisodicLife(inner)
        env.reset()
        resets_before = inner.full_resets
        # Steps 1..5: the 5th loses a life -> wrapper terminal.
        flags = [env.step(0).terminated for _ in range(5)]
        assert flags == [False] * 4 + [True]
        # Learner-side reset: no real reset; the no-op step advances t.
        obs = env.reset()
        assert inner.full_resets == resets_before
        assert obs[0, 0, 0] == 6  # t continued past the death frame
        # Second life plays out the same way.
        flags = [env.step(0).terminated for _ in range(4)]
        assert flags == [False] * 3 + [True]  # t=10: second life lost

    def test_episodic_life_full_reset_on_game_over(self):
        inner = FakeAtariEnv(lives=2, steps_per_life=3)
        env = EpisodicLife(inner)
        env.reset()
        resets_before = inner.full_resets
        # Life 1 lost at t=3 (wrapper terminal), life 2 (final) at t=6 —
        # the env itself terminates; the next reset must be real.
        for _ in range(3):
            r = env.step(0)
        assert r.terminated
        env.reset()  # fake (no-op) reset
        for _ in range(2):
            r = env.step(0)
        assert r.terminated  # t=6: game over
        obs = env.reset()
        assert inner.full_resets == resets_before + 1
        assert obs[0, 0, 0] == 0  # t restarted

    def test_no_op_reset_hitting_game_over_falls_through(self):
        """EpisodicLife's subtle branch: when the post-death no-op step
        itself ends the game, reset must fall through to a REAL reset so
        no episode starts on a game-over frame."""
        # steps_per_life=1: every step loses a life; 2 lives total.
        inner = FakeAtariEnv(lives=2, steps_per_life=1)
        env = EpisodicLife(inner)
        env.reset()
        r = env.step(0)   # t=1: life 1 lost -> wrapper terminal, game alive
        assert r.terminated
        resets_before = inner.full_resets
        obs = env.reset()  # no-op step at t=2 loses the LAST life
        assert inner.full_resets == resets_before + 1
        assert obs[0, 0, 0] == 0

    def test_reward_clip_on_unclipped_rewards(self):
        env = RewardClip(FakeAtariEnv(lives=9, steps_per_life=10_000,
                                      reward_every=2, reward=7.0))
        env.reset()
        rewards = [env.step(0).reward for _ in range(6)]
        assert rewards == [0.0, 1.0, 0.0, 1.0, 0.0, 1.0]

    def test_full_stack_shapes_and_factory(self):
        from ape_x_dqn_tpu.envs import make_env

        env = make_env("fake-atari", frame_skip=4, frame_stack=4)
        assert env.observation_shape == (84, 84, 4)
        obs = env.reset()
        assert obs.shape == (84, 84, 4) and obs.dtype == np.uint8
        r = env.step(1)
        assert r.obs.shape == (84, 84, 4)
        assert -1.0 <= r.reward <= 1.0

    def test_full_stack_trains_end_to_end(self):
        """The flagship conv path on the fake-ALE stack: actors roll real
        84×84 frames through EpisodicLife+FrameSkip+preprocess and the
        learner trains — Atari-shaped end-to-end without ALE."""
        from ape_x_dqn_tpu.config import ApexConfig
        from ape_x_dqn_tpu.runtime import SingleProcessDriver

        cfg = ApexConfig()
        cfg.env.name = "fake-atari"
        cfg.network = "conv"
        cfg.actor.num_actors = 2
        cfg.actor.flush_every = 8
        cfg.learner.min_replay_mem_size = 64
        cfg.learner.replay_sample_size = 16
        cfg.learner.optimizer = "adam"
        cfg.replay.capacity = 1024
        cfg.validate()
        driver = SingleProcessDriver(cfg)
        results = driver.run(learner_steps=3)
        losses = [r.loss for r in results if np.isfinite(r.loss)]
        assert losses, "no learner steps ran"
        assert all(np.isfinite(l) for l in losses)
        batch = driver.replay.sample(8, rng=np.random.default_rng(0))
        assert batch.transition.obs.shape[1:] == (84, 84, 1)
