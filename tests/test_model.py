"""Dueling network tests: shapes, aggregation semantics, dtype policy."""

import jax
import jax.numpy as jnp
import numpy as np

from ape_x_dqn_tpu.models.dueling import DuelingDQN, DuelingMLP, build_network


def _init_apply(net, obs):
    params = net.init(jax.random.PRNGKey(0), obs)
    return params, net.apply(params, obs)


def test_conv_output_shapes():
    net = DuelingDQN(num_actions=6, compute_dtype=jnp.float32)
    obs = jnp.zeros((2, 84, 84, 1), jnp.uint8)
    _, (v, a, q) = _init_apply(net, obs)
    assert v.shape == (2, 1)
    assert a.shape == (2, 6)
    assert q.shape == (2, 6)
    assert q.dtype == jnp.float32


def test_dueling_aggregation_per_row_mean():
    # Q = V + A - mean_a(A) per row (intended semantics of the reference's
    # duelling_network.py:27, which wrongly reduces over the whole batch).
    net = DuelingMLP(num_actions=3)
    obs = jax.random.normal(jax.random.PRNGKey(1), (4, 8))
    _, (v, a, q) = _init_apply(net, obs)
    expected = np.asarray(v) + np.asarray(a) - np.asarray(a).mean(axis=1, keepdims=True)
    np.testing.assert_allclose(np.asarray(q), expected, rtol=1e-5)
    # identifiability: mean_a Q == V per row
    np.testing.assert_allclose(
        np.asarray(q).mean(axis=1), np.asarray(v)[:, 0], rtol=1e-5
    )


def test_aggregation_independent_across_batch():
    # Row i's Q must not change when other rows change (batch-mean bug guard).
    net = DuelingMLP(num_actions=3)
    obs1 = jax.random.normal(jax.random.PRNGKey(2), (2, 8))
    params = net.init(jax.random.PRNGKey(0), obs1)
    q_full = net.apply(params, obs1)[2]
    q_row0 = net.apply(params, obs1[:1])[2]
    np.testing.assert_allclose(np.asarray(q_full[:1]), np.asarray(q_row0), rtol=1e-5)


def test_uint8_and_float_inputs_agree():
    net = DuelingDQN(num_actions=4, compute_dtype=jnp.float32)
    obs_u8 = jax.random.randint(jax.random.PRNGKey(3), (1, 84, 84, 1), 0, 255).astype(jnp.uint8)
    params = net.init(jax.random.PRNGKey(0), obs_u8)
    q_u8 = net.apply(params, obs_u8)[2]
    q_f = net.apply(params, obs_u8.astype(jnp.float32) / 255.0)[2]
    np.testing.assert_allclose(np.asarray(q_u8), np.asarray(q_f), rtol=1e-5)


def test_reference_parity_channel_widths():
    # Reference uses 64/64/64 (SURVEY §2 comp 5); "nature" option gives 32/64/64.
    assert DuelingDQN(num_actions=4).channels == (64, 64, 64)
    assert build_network("nature", 4).channels == (32, 64, 64)


def test_bfloat16_compute_float32_params():
    net = DuelingDQN(num_actions=4)  # default bfloat16 compute
    obs = jnp.zeros((1, 84, 84, 1), jnp.uint8)
    params = net.init(jax.random.PRNGKey(0), obs)
    dtypes = {p.dtype for p in jax.tree_util.tree_leaves(params)}
    assert dtypes == {jnp.dtype(jnp.float32)}
    q = net.apply(params, obs)[2]
    assert q.dtype == jnp.float32
