"""CLI trainer tests: both modes end-to-end via main(argv)."""

import json

import pytest

from ape_x_dqn_tpu.train import main

BASE_ARGS = [
    "--set", "env.name=chain:6",
    "--set", "network=mlp",
    "--set", "actor.num_actors=2",
    "--set", "actor.flush_every=8",
    "--set", "learner.min_replay_mem_size=128",
    "--set", "replay.capacity=2000",
    "--set", "learner.optimizer=adam",
    "--log-every", "20",
]


def test_sync_mode(capsys, tmp_path):
    rc = main(BASE_ARGS + ["--mode", "sync", "--steps", "40",
                           "--metrics-file", str(tmp_path / "m.jsonl")])
    assert rc == 0
    out = capsys.readouterr().out
    records = [json.loads(l) for l in out.splitlines() if l.strip()]
    assert records and records[-1].get("final")
    assert records[-1]["step"] == 40
    assert (tmp_path / "m.jsonl").read_text().strip()


def test_async_mode(capsys):
    rc = main(BASE_ARGS + ["--mode", "async", "--steps", "60"])
    assert rc == 0
    out = capsys.readouterr().out
    records = [json.loads(l) for l in out.splitlines() if l.strip()]
    assert records[-1]["step"] == 60
    assert records[-1]["replay_size"] >= 128


def test_reference_params_file(tmp_path, capsys):
    """The actual reference parameters.json vocabulary drives the CLI."""
    ref = {
        "env_conf": {"state_shape": [6], "action_dim": 2, "name": "chain:6"},
        "Actor": {"num_actors": 2, "T": 1000, "num_steps": 3, "epsilon": 0.4,
                  "alpha": 7, "gamma": 0.9, "n_step_transition_batch_size": 8,
                  "Q_network_sync_freq": 50},
        "Learner": {"remove_old_xp_freq": 100, "q_target_sync_freq": 100,
                    "min_replay_mem_size": 128, "replay_sample_size": 16,
                    "load_saved_state": False},
        "Replay_Memory": {"soft_capacity": 2000, "priority_exponent": 0.6,
                          "importance_sampling_exponent": 0.4},
    }
    f = tmp_path / "params.json"
    f.write_text(json.dumps(ref))
    rc = main(["--params-file", str(f), "--set", "network=mlp",
               "--mode", "sync", "--steps", "10", "--log-every", "5"])
    assert rc == 0


def test_bad_override_exits_with_error():
    with pytest.raises(ValueError):
        main(BASE_ARGS + ["--set", "bogus.key=1", "--steps", "1"])
