"""CLI trainer tests: both modes end-to-end via main(argv)."""

import json

import pytest

from ape_x_dqn_tpu.train import main

BASE_ARGS = [
    "--set", "env.name=chain:6",
    "--set", "network=mlp",
    "--set", "actor.num_actors=2",
    "--set", "actor.flush_every=8",
    "--set", "learner.min_replay_mem_size=128",
    "--set", "replay.capacity=2000",
    "--set", "learner.optimizer=adam",
    "--log-every", "20",
]


def test_sync_mode(capsys, tmp_path):
    rc = main(BASE_ARGS + ["--mode", "sync", "--steps", "40",
                           "--metrics-file", str(tmp_path / "m.jsonl")])
    assert rc == 0
    out = capsys.readouterr().out
    records = [json.loads(l) for l in out.splitlines() if l.strip()]
    assert records and records[-1].get("final")
    assert records[-1]["step"] == 40
    assert (tmp_path / "m.jsonl").read_text().strip()


def test_async_mode(capsys):
    rc = main(BASE_ARGS + ["--mode", "async", "--steps", "60"])
    assert rc == 0
    out = capsys.readouterr().out
    records = [json.loads(l) for l in out.splitlines() if l.strip()]
    assert records[-1]["step"] == 60
    assert records[-1]["replay_size"] >= 128


def test_reference_params_file(tmp_path, capsys):
    """The actual reference parameters.json vocabulary drives the CLI."""
    ref = {
        "env_conf": {"state_shape": [6], "action_dim": 2, "name": "chain:6"},
        "Actor": {"num_actors": 2, "T": 1000, "num_steps": 3, "epsilon": 0.4,
                  "alpha": 7, "gamma": 0.9, "n_step_transition_batch_size": 8,
                  "Q_network_sync_freq": 50},
        "Learner": {"remove_old_xp_freq": 100, "q_target_sync_freq": 100,
                    "min_replay_mem_size": 128, "replay_sample_size": 16,
                    "load_saved_state": False},
        "Replay_Memory": {"soft_capacity": 2000, "priority_exponent": 0.6,
                          "importance_sampling_exponent": 0.4},
    }
    f = tmp_path / "params.json"
    f.write_text(json.dumps(ref))
    rc = main(["--params-file", str(f), "--set", "network=mlp",
               "--mode", "sync", "--steps", "10", "--log-every", "5"])
    assert rc == 0


def test_bad_override_exits_with_error():
    with pytest.raises(ValueError):
        main(BASE_ARGS + ["--set", "bogus.key=1", "--steps", "1"])


def test_canonical_configs_load_and_validate():
    """The committed canonical configs (the five BASELINE.md training
    profiles + the serving profile) parse, validate, and carry the runtime
    modes they claim (device replay, data parallel, process actors,
    frame compression, serving buckets)."""
    import glob
    import os

    from ape_x_dqn_tpu.config import load_config

    root = os.path.join(os.path.dirname(__file__), "..", "configs")
    paths = sorted(glob.glob(os.path.join(root, "*.json")))
    assert len(paths) == 6, paths
    cfgs = {os.path.basename(p): load_config(p) for p in paths}
    assert cfgs["config1_pong_1actor.json"].actor.num_actors == 1
    assert cfgs["config2_breakout_8actors.json"].actor.num_actors == 8
    c3 = cfgs["config3_seaquest_256actors_2m.json"]
    assert c3.replay.capacity == 2_000_000
    # Paper scale runs the frame-dedup sharded HBM ring (round-4 verdict
    # item 1a): frames stored ONCE, so the 2M ring is capacity ×
    # frame_ratio × 7056 B ≈ 17.6 GB global ≈ 4.4 GB/chip at dp=4 — the
    # double-store's 28 GB could not fit and round 4 fell back to a host
    # replay that sampled below the learner rate.
    assert c3.learner.device_replay and c3.replay.dedup
    assert c3.learner.data_parallel == 4
    per_chip = (
        c3.replay.capacity * c3.replay.frame_ratio * 84 * 84
        / c3.learner.data_parallel
    )
    assert per_chip < 6e9, "config3 ring shard must fit a 16 GB chip easily"
    assert c3.actor.mode == "process"
    assert c3.actor.num_actors // c3.actor.num_workers >= c3.learner.data_parallel
    c4 = cfgs["config4_dp_v4_8_512actors.json"]
    assert c4.learner.data_parallel == 4 and c4.actor.num_actors == 512
    # The north-star mode (BASELINE config 4): fused HBM replay sharded
    # over the DP mesh — 2M slots / 4 devices ≈ 7 GB/device of rings,
    # sized for a v4-8's 32 GB/chip HBM (not single-chip v5e).
    assert c4.learner.device_replay and c4.learner.sample_ahead
    c5 = cfgs["config5_sweep_atari57_base.json"]
    assert c5.learner.device_replay
    c6 = cfgs["config6_serving_cpu.json"]
    assert c6.network == "conv"
    assert c6.serving.max_batch == 32
    assert c6.serving.queue_capacity >= c6.serving.max_batch


def test_sweep_runner_shared_schedule(tmp_path):
    """tools/sweep.py (BASELINE config 5's runner): one run per game under
    one shared schedule, summary JSONL written, bad games don't kill it."""
    import json
    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent.parent / "tools"))
    try:
        import sweep
    finally:
        sys.path.pop(0)

    out = tmp_path / "sweep.jsonl"
    results = sweep.run_sweep(
        ["chain:5", "catch", "definitely-not-an-env"],
        steps=20,
        mode="sync",
        out_path=str(out),
        overrides=[
            "network=mlp", "actor.num_actors=2", "actor.T=100000",
            "learner.min_replay_mem_size=64", "replay.capacity=1024",
        ],
    )
    assert [r["status"] for r in results] == ["ok", "ok", "error"]
    assert results[0]["game"] == "chain:5"
    lines = [json.loads(l) for l in out.read_text().splitlines()]
    assert len(lines) == 3
    # Shared schedule, distinct seeds per game.
    assert lines[0]["seed"] != lines[1]["seed"]


def test_sweep_atari57_list():
    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent.parent / "tools"))
    try:
        import sweep
    finally:
        sys.path.pop(0)
    games = sweep.game_list("atari57")
    assert len(games) == 57
    assert "PongNoFrameskip-v4" in games and "ZaxxonNoFrameskip-v4" in games
