"""Fleet observability plane (ISSUE 14): SLO rule evaluation, the
rollup aggregator's merge/liveness contracts, the fleet schema pins,
and cross-tier trace propagation — including the e2e pin that one
trace id surfaces in spans from >= 3 distinct pids across the
replay-RPC and inference hops, and the trace-field-off wire staying
bit-identical to the pre-flags frames."""

from __future__ import annotations

import json
import os
import struct
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

from ape_x_dqn_tpu.obs.fleet import (
    FleetAggregator,
    SloEngine,
    SloRule,
    _endpoints_down,
    rules_from_config,
)
from ape_x_dqn_tpu.utils.metrics import LatencyHistogram

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OBS = (6,)


def _doc_keys(section_header):
    from ape_x_dqn_tpu.analysis.metrics_doc import doc_section_keys

    return doc_section_keys(
        section_header, os.path.join(REPO, "docs", "METRICS.md"))


# ---------------------------------------------------------------------------
# SLO engine units: breach, burn window, clear, flap damping.
# ---------------------------------------------------------------------------


def _engine(emit_list, *, bound=100.0, kind="upper", window_s=10.0,
            burn=0.5, clear=0.1, min_samples=3):
    return SloEngine(
        [SloRule("r", kind, bound, lambda r: r.get("v"))],
        window_s=window_s, burn_threshold=burn, clear_threshold=clear,
        min_samples=min_samples,
        emit=lambda name, **f: emit_list.append((name, f)),
    )


class TestSloEngine:
    def test_single_bad_sample_is_not_a_breach(self):
        events = []
        eng = _engine(events, min_samples=3)
        eng.evaluate({"v": 500.0}, now=0.0)
        eng.evaluate({"v": 50.0}, now=1.0)
        assert eng.rules[0].state == "ok" and not events

    def test_breach_fires_at_burn_threshold_then_clears(self):
        events = []
        eng = _engine(events)
        t = 0.0
        for _ in range(4):
            eng.evaluate({"v": 500.0}, now=t)
            t += 1.0
        assert eng.rules[0].state == "breach"
        assert [e[0] for e in events] == ["slo_breach"]
        ev = events[0][1]
        assert ev["rule"] == "r" and ev["bound"] == 100.0 \
            and ev["burn"] >= 0.5
        # Recovery: good samples push burn under clear_threshold only
        # once the bad window expires.
        for _ in range(20):
            eng.evaluate({"v": 10.0}, now=t)
            t += 1.0
        assert eng.rules[0].state == "ok"
        assert [e[0] for e in events] == ["slo_breach", "slo_clear"]

    def test_burn_window_expires_old_samples(self):
        events = []
        eng = _engine(events, window_s=5.0)
        eng.evaluate({"v": 500.0}, now=0.0)
        eng.evaluate({"v": 500.0}, now=1.0)
        # 10s later the bad samples left the window: three fresh good
        # samples keep the rule ok even though 2/5 lifetime were bad.
        for t in (10.0, 11.0, 12.0):
            eng.evaluate({"v": 10.0}, now=t)
        assert eng.rules[0].state == "ok" and not events

    def test_flapping_is_damped_by_hysteresis(self):
        """A value oscillating across the bound every sweep holds burn
        ~0.5 — above clear (0.2), below breach (0.8) after the initial
        window: NO transition storm (the band is the contract)."""
        events = []
        eng = _engine(events, burn=0.8, clear=0.2)
        t = 0.0
        for i in range(60):
            eng.evaluate({"v": 500.0 if i % 2 else 10.0}, now=t)
            t += 1.0
        assert len(events) <= 1   # at most one initial transition, no storm

    def test_lower_bound_rule_and_none_skips(self):
        events = []
        eng = SloEngine(
            [SloRule("qps", "lower", 10.0, lambda r: r.get("qps"))],
            window_s=10.0, burn_threshold=0.5, clear_threshold=0.1,
            min_samples=2,
            emit=lambda name, **f: events.append((name, f)),
        )
        t = 0.0
        for _ in range(4):
            eng.evaluate({}, now=t)       # unmeasurable: skipped entirely
            t += 1.0
        assert eng.rules[0].state == "ok" and not events
        assert eng.rules[0]._window == eng.rules[0]._window  # no samples
        for _ in range(3):
            eng.evaluate({"qps": 2.0}, now=t)
            t += 1.0
        assert eng.rules[0].state == "breach"
        assert events[0][1]["kind"] == "lower"

    def test_threshold_ordering_validated(self):
        with pytest.raises(ValueError):
            SloEngine([], burn_threshold=0.2, clear_threshold=0.5)

    def test_rules_from_config_defaults_and_knobs(self):
        from ape_x_dqn_tpu.config import ApexConfig

        cfg = ApexConfig()
        names = {r.name for r in rules_from_config(cfg.obs)}
        assert names == {"endpoints_alive"}   # only liveness by default
        cfg.obs.fleet_slo_age_p95_ms = 2000.0
        cfg.obs.fleet_slo_serving_p99_ms = 50.0
        cfg.obs.fleet_slo_serving_qps_min = 5.0
        cfg.obs.fleet_slo_ring_occupancy_high = 0.9
        cfg.obs.fleet_slo_inference_rtt_p99_ms = 100.0
        cfg.validate()
        names = {r.name for r in rules_from_config(cfg.obs)}
        assert names == {
            "endpoints_alive", "age_p95_ms", "serving_p99_ms",
            "serving_qps", "ring_occupancy", "inference_rtt_p99_ms",
        }

    def test_config_validation_rejects_bad_bands(self):
        from ape_x_dqn_tpu.config import ApexConfig

        cfg = ApexConfig()
        cfg.obs.fleet_slo_clear_threshold = 0.9   # > burn_threshold
        with pytest.raises(ValueError, match="clear"):
            cfg.validate()
        cfg = ApexConfig()
        cfg.obs.fleet_slo_ring_occupancy_low = 0.8
        cfg.obs.fleet_slo_ring_occupancy_high = 0.5
        with pytest.raises(ValueError, match="occupancy"):
            cfg.validate()


# ---------------------------------------------------------------------------
# Aggregator: merge + liveness + schema.
# ---------------------------------------------------------------------------


def _fake_trainer_varz(age_values=(0.5, 1.0, 2.0), spans=()):
    """A registry shaped like a trainer's /varz, served over HTTP."""
    from ape_x_dqn_tpu.obs.exporter import ObsServer
    from ape_x_dqn_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()
    h = LatencyHistogram(min_s=1e-3, max_s=7200.0, per_decade=10)
    for v in age_values:
        h.record(v)
    reg.register_provider("lineage", lambda: {
        "age_at_sample": {"count": h.count, "buckets_s": h.buckets()},
    })
    reg.register_provider("trace_spans", lambda: {
        "recorded": len(spans), "spans": list(spans),
    })
    reg.register_provider("learner", lambda: {
        "step": 7, "steps_per_sec": 3.0,
    })
    return ObsServer(reg), h


@pytest.fixture
def shard():
    from ape_x_dqn_tpu.replay.buffer import PrioritizedReplay
    from ape_x_dqn_tpu.replay.service import ReplayShardServer

    rep = PrioritizedReplay(256, OBS)
    srv = ReplayShardServer(rep, 0, token=5, codec="off").start()
    yield rep, srv
    srv.close()


def _endpoints_file(tmp_path, srv):
    path = str(tmp_path / "endpoints.json")
    with open(path, "w") as f:
        json.dump({
            "token": srv.token, "codec": "off", "total_capacity": 256,
            "shards": [{"id": 0, "host": "127.0.0.1", "port": srv.port,
                        "base": 0, "capacity": 256,
                        "incarnation": srv.incarnation}],
        }, f)
    return path


class TestFleetAggregator:
    def test_rollup_merges_and_marks_dead_endpoint(self, shard, tmp_path):
        rep, srv = shard
        t1, h1 = _fake_trainer_varz(age_values=(0.5, 1.0))
        t2, h2 = _fake_trainer_varz(age_values=(2.0, 4.0, 8.0))
        events = []
        agg = FleetAggregator(
            slo=SloEngine(
                [SloRule("endpoints_alive", "upper", 0.0, _endpoints_down)],
                window_s=60.0, min_samples=2,
            ),
            emit=lambda name, **f: events.append((name, f)),
        )
        try:
            agg.add_varz("trainer_a", t1.url)
            agg.add_varz("trainer_b", t2.url)
            agg.add_varz("dead", "http://127.0.0.1:1/varz", kind="replica")
            agg.watch_replay_endpoints(_endpoints_file(tmp_path, srv))
            for i in range(3):
                rollup = agg.scrape_once(now=float(i))
            eps = rollup["endpoints"]
            assert set(eps) == {"trainer_a", "trainer_b", "dead",
                                "replay_shard0"}
            assert eps["trainer_a"]["alive"] and eps["replay_shard0"]["alive"]
            assert not eps["dead"]["alive"]
            assert eps["dead"]["scrape_failures"] == 3
            assert rollup["alive"] == 3 and rollup["expected"] == 4
            # Age histograms merged BUCKET-WISE across both trainers.
            age = rollup["age_of_experience"]
            assert age["count"] == 5
            ref = LatencyHistogram(min_s=1e-3, max_s=7200.0, per_decade=10)
            ref.merge(h1)
            ref.merge(h2)
            assert age["buckets_s"] == ref.buckets()
            # Shard scraped over its own stats RPC; counters summed in.
            assert rollup["replay"]["shards_alive"] == 1
            assert rollup["replay"]["requests"] >= 1
            # One sustained dead endpoint = a liveness breach.
            assert [e[0] for e in events] == ["slo_breach"]
        finally:
            agg.close()
            t1.close()
            t2.close()

    def test_rollup_serves_and_never_503s_on_member_death(self, tmp_path):
        t1, _h = _fake_trainer_varz()
        agg = FleetAggregator()
        try:
            agg.add_varz("trainer", t1.url)
            agg.add_varz("dead", "http://127.0.0.1:1/varz")
            agg.scrape_once(now=0.0)
            obs = agg.serve(port=0)
            snap = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{obs.port}/varz", timeout=5.0))
            assert "fleet" in snap and "slo" in snap
            assert not snap["fleet"]["endpoints"]["dead"]["alive"]
            # The rollup's own health is its scrape loop — 200 despite
            # the dead member.
            with urllib.request.urlopen(
                f"http://127.0.0.1:{obs.port}/healthz", timeout=5.0
            ) as r:
                assert r.status == 200
            prom = urllib.request.urlopen(
                f"http://127.0.0.1:{obs.port}/metrics", timeout=5.0
            ).read().decode()
            assert "apex_fleet_scrape_failures" in prom
        finally:
            agg.close()
            t1.close()

    def test_fleet_and_slo_sections_match_doc(self, tmp_path):
        t1, _h = _fake_trainer_varz()
        agg = FleetAggregator()
        try:
            agg.add_varz("trainer", t1.url)
            rollup = agg.scrape_once(now=0.0)
        finally:
            agg.close()
            t1.close()
        doc = _doc_keys("## Fleet rollup schema")
        assert doc, "Fleet rollup schema doc section missing"
        assert set(doc) == set(rollup), set(doc) ^ set(rollup)
        slo_doc = _doc_keys("## SLO schema")
        assert slo_doc, "SLO schema doc section missing"
        status = SloEngine([SloRule("x", "upper", 1.0, lambda r: 0.0)]) \
            .status()
        assert set(slo_doc) == set(status), set(slo_doc) ^ set(status)

    def test_timeline_assembly_requires_two_pids(self):
        agg = FleetAggregator()
        agg._fold_traces([
            {"trace_id": 9, "hop": "act", "pid": 1, "t0_s": 1.0,
             "t1_s": 1.0, "dur_ms": 0.0},
            {"trace_id": 9, "hop": "rsvc.add", "pid": 2, "t0_s": 1.1,
             "t1_s": 1.3, "dur_ms": 200.0},
            {"trace_id": 8, "hop": "rsvc.add.client", "pid": 3,
             "t0_s": 2.0, "t1_s": 2.1, "dur_ms": 100.0},
        ])
        tl = agg._timelines()
        assert [t["trace_id"] for t in tl] == [9]   # single-pid 8 filtered
        assert tl[0]["pids"] == [1, 2]
        assert tl[0]["hops"] == ["act", "rsvc.add"]


# ---------------------------------------------------------------------------
# Wire pins: trace off = bit-identical frames; version-gated hellos.
# ---------------------------------------------------------------------------


class TestTraceWire:
    def test_serve_hello_flags_off_is_preflags_bytes(self):
        from ape_x_dqn_tpu.runtime.net import (
            SERVE_HELLO,
            SERVE_MAGIC,
            SERVE_VERSION_EXT,
            serve_hello_ext_bytes,
        )

        legacy = SERVE_HELLO.pack(SERVE_MAGIC, SERVE_VERSION_EXT) + \
            struct.Struct("<qqqB7x").pack(3, 2, 99, 1)
        assert serve_hello_ext_bytes(3, 2, 99, 1) == legacy

    def test_rsvc_hello_flags_off_is_preflags_bytes(self):
        from ape_x_dqn_tpu.replay.service import (
            RSVC_HELLO,
            RSVC_MAGIC,
            RSVC_VERSION,
        )

        legacy = struct.Struct("<4sIqqqqB7x").pack(
            RSVC_MAGIC, RSVC_VERSION, 9, 0, -1, 5, 0)
        assert RSVC_HELLO.pack(RSVC_MAGIC, RSVC_VERSION, 9, 0, -1, 5,
                               0, 0) == legacy

    def test_preflags_raw_client_still_served(self, shard):
        """A client speaking the OLD hello struct byte-for-byte (no
        flags knowledge at all) handshakes and gets its add applied —
        today's wire is a valid member of tomorrow's fleet."""
        import socket

        from ape_x_dqn_tpu.replay.service import (
            _RPC,
            OP_DIGEST,
            RSVC_ACK,
            RSVC_MAGIC,
            RSVC_VERSION,
        )
        from ape_x_dqn_tpu.runtime.net import F_RREQ, FrameParser, frame_bytes

        rep, srv = shard
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=5.0)
        s.sendall(struct.Struct("<4sIqqqqB7x").pack(
            RSVC_MAGIC, RSVC_VERSION, 9, 0, -1, srv.token, 0))
        s.settimeout(5.0)
        ack = b""
        while len(ack) < RSVC_ACK.size:
            ack += s.recv(RSVC_ACK.size - len(ack))
        s.sendall(frame_bytes(F_RREQ, 1, [_RPC.pack(1, OP_DIGEST)]))
        parser = FrameParser()
        deadline = time.monotonic() + 5.0
        got = None
        while got is None and time.monotonic() < deadline:
            parser.feed(s.recv(1 << 16))
            got = parser.next()
        assert got is not None and srv.torn_frames == 0
        s.close()

    def test_traced_payload_is_prefix_plus_legacy(self):
        from ape_x_dqn_tpu.runtime.net import split_trace, wrap_trace

        body = b"legacy-request-bytes"
        wrapped = wrap_trace(1234, body)
        assert wrapped[8:] == body
        tid, rest = split_trace(wrapped)
        assert tid == 1234 and bytes(rest) == body
        with pytest.raises(ValueError):
            split_trace(b"short")

    def test_untraced_clients_record_no_spans(self, shard):
        from ape_x_dqn_tpu.replay.service import ShardedReplayClient

        rep, srv = shard
        cl = ShardedReplayClient(
            [{"id": 0, "host": "127.0.0.1", "port": srv.port, "base": 0,
              "capacity": 256, "incarnation": srv.incarnation}],
            token=srv.token, codec="off", trace=False,
            request_timeout_s=5.0,
        )
        try:
            arrays = _chunk()
            cl.add(arrays["prio"], _Batch(arrays), trace_id=999)
            assert cl.spans.snapshot()["spans"] == []
            assert srv.stats()["trace_spans"]["spans"] == []
        finally:
            cl.close()


def _chunk(n=8, seed=0):
    r = np.random.default_rng(seed)
    return {
        "prio": (np.abs(r.normal(size=n)) + 0.1).astype(np.float64),
        "obs": r.integers(0, 255, (n, *OBS), dtype=np.uint8),
        "action": r.integers(0, 2, n).astype(np.int32),
        "reward": r.normal(size=n).astype(np.float32),
        "discount": np.full(n, 0.99, np.float32),
        "next_obs": r.integers(0, 255, (n, *OBS), dtype=np.uint8),
    }


class _Batch:
    def __init__(self, arrays):
        for k, v in arrays.items():
            setattr(self, k, v)


# ---------------------------------------------------------------------------
# Cross-tier e2e: one trace id, >= 3 distinct pids, both RPC planes.
# ---------------------------------------------------------------------------

_SERVING_CHILD = r"""
import concurrent.futures, json, os, sys
import numpy as np
from ape_x_dqn_tpu.serving.net_server import ServingNetServer
from ape_x_dqn_tpu.serving.batcher import ServedAction


class Stub:
    param_version = 3

    def submit(self, obs):
        f = concurrent.futures.Future()
        f.set_result(ServedAction(1, np.zeros(2, np.float32), 3, 0.0))
        return f


srv = ServingNetServer(Stub()).start()
print(json.dumps({"port": srv.port, "pid": os.getpid()}), flush=True)
sys.stdin.readline()
print(json.dumps(srv.stats()["recent_spans"]), flush=True)
srv.close()
"""


class TestCrossTierTraceE2E:
    def test_same_trace_id_in_three_pids_across_both_planes(self, tmp_path):
        """The acceptance pin: ONE trace id appears in spans recorded by
        >= 3 distinct processes, across the replay-RPC hops (client in
        this pid, shard server in its own) AND the inference hops
        (serving replica in a third pid) — and the aggregator assembles
        them into one timeline."""
        from ape_x_dqn_tpu.replay.service import ShardClient, \
            ShardedReplayClient
        from ape_x_dqn_tpu.serving.central import CentralInferenceClient

        env = {**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"}
        tid = 0x7ACE

        # Shard in its own process (numpy-only CLI, sub-second spawn).
        shard_proc = subprocess.Popen(
            [sys.executable, "-m", "ape_x_dqn_tpu.replay.service",
             "--shard-id", "0", "--capacity", "256", "--obs-shape", "6",
             "--token", "5", "--port", "0", "--codec", "off"],
            cwd=REPO, env=env, stdin=subprocess.DEVNULL,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        )
        serve_proc = subprocess.Popen(
            [sys.executable, "-c", _SERVING_CHILD],
            cwd=REPO, env=env, stdin=subprocess.PIPE,
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        )
        cl = None
        inf = None
        try:
            announce = json.loads(shard_proc.stdout.readline())
            assert announce["event"] == "replay_shard_listen"
            shard_pid, shard_port = announce["pid"], announce["port"]

            # Replay plane: traced add + sample + write-back.
            cl = ShardedReplayClient(
                [{"id": 0, "host": "127.0.0.1", "port": shard_port,
                  "base": 0, "capacity": 256, "incarnation": 0}],
                token=5, codec="off", trace=True, request_timeout_s=10.0,
            )
            arrays = _chunk()
            idx = cl.add(arrays["prio"], _Batch(arrays), trace_id=tid)
            batch = cl.sample(4)
            cl.tag_sample_span(tid)
            cl.update_priorities(batch.indices.astype(np.int64),
                                 np.ones(4), trace_id=tid)
            sc = ShardClient(0, "127.0.0.1", shard_port, token=5,
                             client_id=42, codec="off")
            shard_stats = sc.shard_stats(timeout=10.0)
            sc.close()

            # Inference plane: the SAME trace id through a replica in a
            # third pid.
            serving = json.loads(serve_proc.stdout.readline())
            inf = CentralInferenceClient("127.0.0.1", serving["port"],
                                         wid=1, trace=True)
            inf.select(np.zeros((2, 6), np.uint8), timeout_s=20.0,
                       trace_id=tid)
            serve_proc.stdin.write(b"dump\n")
            serve_proc.stdin.flush()
            replica_spans = json.loads(serve_proc.stdout.readline())

            spans = (
                cl.spans.snapshot()["spans"]
                + inf.spans.snapshot()["spans"]
                + shard_stats["trace_spans"]["spans"]
                + replica_spans["spans"]
            )
            ours = [s for s in spans if s["trace_id"] == tid]
            pids = {s["pid"] for s in ours}
            hops = {s["hop"] for s in ours}
            assert len(pids) >= 3, (pids, hops)
            assert os.getpid() in pids and shard_pid in pids \
                and serving["pid"] in pids
            # Both planes crossed: replay-RPC hops and inference hops.
            assert {"rsvc.add.client", "rsvc.add"} <= hops
            assert "rsvc.update" in hops and "rsvc.sample.client" in hops
            assert {"inf.select.client", "serve.infer"} <= hops
            # And the aggregator folds them into ONE timeline.
            agg = FleetAggregator()
            agg._fold_traces(ours)
            tl = agg._timelines()
            assert len(tl) == 1 and tl[0]["trace_id"] == tid
            assert len(tl[0]["pids"]) >= 3
        finally:
            if cl is not None:
                cl.close()
            if inf is not None:
                inf.close()
            for p in (shard_proc, serve_proc):
                p.terminate()
                try:
                    p.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(timeout=5.0)
                if p.stdout is not None:
                    p.stdout.close()
                if p.stdin is not None:
                    p.stdin.close()


class TestTraceThroughRouter:
    def test_traced_request_splices_intact_through_router(self):
        """The router balances CONNECTIONS and never parses frames — a
        trace-negotiated hello + trace-prefixed request must ride the
        splice byte-for-byte and surface as a server-side span."""
        import concurrent.futures

        from ape_x_dqn_tpu.serving.batcher import ServedAction
        from ape_x_dqn_tpu.serving.net_server import (
            ServingClient,
            ServingNetServer,
        )
        from ape_x_dqn_tpu.serving.router import ServingRouter

        class _Stub:
            param_version = 1

            def submit(self, obs):
                f = concurrent.futures.Future()
                f.set_result(ServedAction(0, np.zeros(2, np.float32), 1,
                                          0.0))
                return f

        srv = ServingNetServer(_Stub()).start()
        router = ServingRouter(port=0)
        router.set_endpoint(0, "127.0.0.1", srv.port)
        router.start()
        cl = ServingClient("127.0.0.1", router.port, trace=True)
        try:
            cl.act(np.zeros(OBS, np.uint8), timeout=15.0, trace_id=4321)
            deadline = time.monotonic() + 5.0
            spans = []
            while time.monotonic() < deadline and not spans:
                spans = [s for s in srv.stats()["recent_spans"]["spans"]
                         if s["trace_id"] == 4321]
                time.sleep(0.05)
            assert spans and spans[0]["hop"] == "serve.request"
            assert srv.stats()["torn_frames"] == 0
        finally:
            cl.close()
            router.close()
            srv.close()


# ---------------------------------------------------------------------------
# Worker trace sweep (the pool's shm-event-ring half).
# ---------------------------------------------------------------------------


class TestWorkerTraceSweep:
    def test_trace_chunk_events_lift_into_act_spans(self):
        from ape_x_dqn_tpu.obs.shm_stats import WORKER_SLOTS, WorkerStatsBlock
        from ape_x_dqn_tpu.runtime.process_actors import ProcessActorPool

        blk = WorkerStatsBlock(slots=WORKER_SLOTS)
        try:
            blk.record_event({"t": 12.5, "kind": "trace_chunk",
                              "trace_id": 321, "rows": 8})
            blk.record_event({"t": 13.0, "kind": "trace_span",
                              "trace_id": 321, "hop": "inf.select.client",
                              "pid": blk.pid, "t0_s": 12.9, "t1_s": 13.0,
                              "dur_ms": 100.0})
            blk.record_event({"t": 13.5, "kind": "error", "error": "x"})

            class _Fake:
                _stats_blocks = {3: blk}

            spans = ProcessActorPool.trace_events(_Fake())
            assert len(spans) == 2
            act = next(s for s in spans if s["hop"] == "act")
            assert act["trace_id"] == 321 and act["wid"] == 3
            assert act["pid"] == blk.pid
            assert any(s["hop"] == "inf.select.client" for s in spans)
        finally:
            blk.close()
            blk.unlink()
